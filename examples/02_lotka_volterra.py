"""Lotka-Volterra ODE with adaptive distance — the headline benchmark.

Reference analog: the pyABC Lotka-Volterra example notebook. 4 parameters
(alpha, beta, gamma, delta), noisy prey/predator trajectories,
AdaptivePNormDistance reweighting each statistic per generation,
MedianEpsilon. On a device-capable setup this runs through the fused
multi-generation kernel (whole chunks of generations per dispatch).

Run: ``python examples/02_lotka_volterra.py`` (env: EX_POP, EX_GENS).
"""
import os
import sys

# make `python examples/<name>.py` work from a repo checkout
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

import numpy as np

import pyabc_tpu as pt
from pyabc_tpu.models import lotka_volterra as lv

POP = int(os.environ.get("EX_POP", 1000))
GENS = int(os.environ.get("EX_GENS", 8))


def main():
    model = lv.make_lv_model()
    prior = lv.default_prior()
    obs = lv.observed_data(seed=123)

    abc = pt.ABCSMC(model, prior, pt.AdaptivePNormDistance(p=2),
                    population_size=POP, eps=pt.MedianEpsilon(), seed=0)
    abc.new("sqlite://", obs)
    history = abc.run(max_nr_populations=GENS)

    df, w = history.get_distribution()
    print("true parameters: ", lv.TRUE_PARS)
    for name in ("alpha", "beta", "gamma", "delta"):
        mu = float(np.sum(df[name] * w))
        print(f"  {name}: posterior mean {mu:.4f} "
              f"(true {lv.TRUE_PARS[name]})")
    eps = history.get_all_populations().query("t >= 0")["epsilon"]
    print("epsilon trajectory:", [round(e, 2) for e in eps])
    # sanity bounds that hold for shrunk smoke-test configs too: a few
    # generations at tiny populations leave the 4-d posterior close to
    # the prior (the median epsilon plateaus near 51 before the schedule
    # bites), so assert INFERENCE PROGRESS (epsilon strictly descended
    # from the calibration level) and prior-support sanity; the tight
    # posterior claim needs the full-size config (alpha ~1.1 at pop
    # 1000 x 8 generations)
    alpha = float(np.sum(df["alpha"] * w))
    assert 0.0 < alpha < 3.0
    assert float(eps.iloc[-1]) < float(eps.iloc[0])
    if POP >= 1000 and GENS >= 8:
        assert abs(alpha - lv.TRUE_PARS["alpha"]) < 0.8
    return history


if __name__ == "__main__":
    main()
