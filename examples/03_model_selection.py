"""Bayesian model selection over two analytically tractable models.

Reference analog: the pyABC model-selection example notebook. Two models
x ~ N(theta, sd_m^2) with different noise levels; the marginal likelihoods
are closed-form, so the posterior model probabilities can be checked
exactly. Each particle carries a model index; the ModelPerturbationKernel
proposes model jumps between generations.

Run: ``python examples/03_model_selection.py`` (env: EX_POP, EX_GENS).
"""
import os
import sys

# make `python examples/<name>.py` work from a repo checkout
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

import numpy as np

import pyabc_tpu as pt
from pyabc_tpu.models import model_selection as msel

POP = int(os.environ.get("EX_POP", 600))
GENS = int(os.environ.get("EX_GENS", 5))
X_OBS = 0.7


def main():
    models, priors, analytic = msel.tractable_pair()
    abc = pt.ABCSMC(models, priors, pt.PNormDistance(p=2),
                    population_size=POP, eps=pt.MedianEpsilon(), seed=7)
    abc.new("sqlite://", {"x": X_OBS})
    history = abc.run(max_nr_populations=GENS)

    probs = history.get_model_probabilities(history.max_t)["p"]
    truth = analytic(X_OBS)
    print("posterior model probabilities:",
          {int(m): round(float(p), 3) for m, p in probs.items()})
    print("analytic (eps -> 0):         ",
          {m: round(float(p), 3) for m, p in enumerate(truth)})
    assert abs(float(probs.get(0, 0.0)) - truth[0]) < 0.25
    return history


if __name__ == "__main__":
    main()
