"""Gaussian conjugate toy — the correctness anchor.

Reference analog: the pyABC quickstart notebook (``doc/examples``):
a 1-d Gaussian mean with a normal prior, where the exact posterior is
available in closed form and the ABC posterior must approach it as the
threshold shrinks.

Run: ``python examples/01_gaussian_toy.py`` (env: EX_POP, EX_GENS).
"""
import os
import sys

# make `python examples/<name>.py` work from a repo checkout
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

import numpy as np

import pyabc_tpu as pt
from pyabc_tpu.models import gaussian

POP = int(os.environ.get("EX_POP", 500))
GENS = int(os.environ.get("EX_GENS", 6))
X_OBS = 1.0


def main():
    model = gaussian.make_mean_only_model(noise_sd=0.5)
    prior = gaussian.mean_only_prior()
    abc = pt.ABCSMC(model, prior, pt.PNormDistance(p=2),
                    population_size=POP, eps=pt.MedianEpsilon(), seed=1)
    abc.new("sqlite://", {"x": X_OBS})
    history = abc.run(max_nr_populations=GENS)

    df, w = history.get_distribution()
    mu = float(np.sum(df["theta"] * w))
    sd = float(np.sqrt(np.sum(w * (df["theta"] - mu) ** 2)))
    mu_true, sd_true = gaussian.conjugate_posterior(X_OBS, noise_sd=0.5)
    print(f"ABC posterior:      mean={mu:.3f} sd={sd:.3f}")
    print(f"analytic posterior: mean={mu_true:.3f} sd={sd_true:.3f}")
    assert abs(mu - mu_true) < 0.3
    return history


if __name__ == "__main__":
    main()
