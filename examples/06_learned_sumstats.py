"""Learned summary statistics (Fearnhead-Prangle) on the fused device path.

Reference analog: the pyABC informative-statistics example
(``pyabc.sumstat.PredictorSumstat``): when the raw statistics mix a weak
signal with high-variance noise dimensions, a regression s(x) ~= E[theta|x]
learned on previous generations concentrates the distance on what matters.
Here the predictor refits between fused device chunks and its transform
runs INSIDE the generation kernel.

Run: ``python examples/06_learned_sumstats.py`` (env: EX_POP, EX_GENS).
"""
import os
import sys

# make `python examples/<name>.py` work from a repo checkout
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

import jax
import numpy as np

import pyabc_tpu as pt

POP = int(os.environ.get("EX_POP", 300))
GENS = int(os.environ.get("EX_GENS", 6))

NOISE_SD = 0.3


def main():
    @pt.JaxModel.from_function(["theta"], name="fp")
    def model(key, theta):
        k1, k2 = jax.random.split(key)
        # 2 informative statistics ... and 4 pure-noise ones that would
        # dominate an unweighted distance
        return {"sig": theta[0] + NOISE_SD * jax.random.normal(k1, (2,)),
                "noise": 5.0 * jax.random.normal(k2, (4,))}

    prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
    abc = pt.ABCSMC(
        model, prior,
        pt.PNormDistance(p=2,
                         sumstat=pt.PredictorSumstat(pt.LinearPredictor())),
        population_size=POP, eps=pt.MedianEpsilon(), seed=42,
        fused_generations=3,
    )
    obs = {"sig": np.asarray([1.0, 1.0]), "noise": np.zeros(4)}
    abc.new("sqlite://", obs)
    history = abc.run(max_nr_populations=GENS)

    df, w = history.get_distribution(0, history.max_t)
    mu = float(np.sum(df["theta"] * w))
    post_mu = 1.0 * (2 / NOISE_SD**2) / (1.0 + 2 / NOISE_SD**2)
    print(f"posterior mean {mu:.3f} (exact {post_mu:.3f})")
    assert abs(mu - post_mu) < 0.35
    return history


if __name__ == "__main__":
    main()
