"""Multi-chip data parallelism: shard the particle axis over a Mesh.

On real hardware pass ``mesh=jax.sharding.Mesh(jax.devices(),
("particles",))`` and every generation's lane batch is sharded across
chips by GSPMD — acceptance accounting and weight normalization become
ICI collectives inserted by the compiler, and the fused multi-generation
chunks run unchanged (ONE host sync per G generations per mesh).

Without a multi-chip machine this example demonstrates the identical
code path on a virtual 8-device CPU platform (the same mechanism the
test suite and the driver's dry run use). Standalone runs force the
virtual platform below; under pytest the suite's conftest already did.

Run: ``python examples/09_multichip_mesh.py`` (env: EX_POP, EX_GENS).
"""
import os
import sys

# make `python examples/<name>.py` work from a repo checkout
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

if __name__ == "__main__" or "XLA_FLAGS" not in os.environ:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if "xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()

import numpy as np

POP = int(os.environ.get("EX_POP", 400))
GENS = int(os.environ.get("EX_GENS", 5))


def main():
    import jax
    from jax.sharding import Mesh

    import pyabc_tpu as pt

    try:
        pool = jax.devices("cpu")
    except RuntimeError:
        pool = jax.devices()
    n_dev = min(8, len(pool))
    mesh = Mesh(np.asarray(pool[:n_dev]), axis_names=("particles",))
    print(f"{n_dev}-device mesh over platform "
          f"{pool[0].platform!r}")

    @pt.JaxModel.from_function(["theta"], name="gauss")
    def model(key, theta):
        return {"x": theta[0] + 0.5 * jax.random.normal(key)}

    # non-adaptive distance => the SHARDED fused path (ISSUE 9): the
    # population axis splits over the mesh via shard_map — per-device
    # lane-key blocks and reservoirs, scalar-column collectives per
    # generation, ONE row all-gather per chunk riding the packed fetch.
    # (An adaptive distance would transparently fall back to the GSPMD
    # replicated path; same API, same results.)
    abc = pt.ABCSMC(
        model, pt.Distribution(theta=pt.RV("norm", 0.0, 1.0)),
        pt.PNormDistance(p=2),
        population_size=POP, eps=pt.MedianEpsilon(),
        seed=7, mesh=mesh, fused_generations=4,
    )
    assert abc._fused_chunk_capable(), "fused multigen path must be active"
    if n_dev > 1 and (n_dev & (n_dev - 1)) == 0:
        assert abc._sharded_n() == n_dev, "sharded path must be active"
    abc.new("sqlite://", {"x": 1.0})
    h = abc.run(max_nr_populations=GENS)
    if abc._engine is not None and abc._engine.mesh_shards:
        ms = abc._engine.snapshot()["mesh"]
        print(f"sharded over {ms['devices']} devices, "
              f"imbalance {ms.get('imbalance')}")
    eps = h.get_all_populations().query("t >= 0")["epsilon"].to_numpy()
    assert (np.diff(eps) < 0).all(), eps
    df, w = h.get_distribution(0, h.max_t)
    mu = float(np.sum(df["theta"] * w))
    post_mu = 0.8  # conjugate normal: var=0.2, mu = 0.2 * 1.0 / 0.25
    assert abs(mu - post_mu) < 0.3, mu
    print(f"sharded fused run OK: {h.n_populations} generations, "
          f"mu={mu:+.3f} (exact {post_mu:+.3f})")
    return h


if __name__ == "__main__":
    main()
