"""External (non-Python) simulator through the file contract.

Reference analog: the pyABC external-simulators example
(``pyabc/external``): the model is an arbitrary executable invoked as
``prog --in params.txt --out sumstats.txt``. Anything that can read and
write key=value text files can be a simulator — here a tiny shell script
stands in for an R/Julia/C++ program (see also
``pyabc_tpu.external.R`` / ``JuliaModel`` for language-specific
adapters).

Run: ``python examples/05_external_model.py`` (env: EX_POP, EX_GENS).
"""
import os
import sys

# make `python examples/<name>.py` work from a repo checkout
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)
import stat
import tempfile

import numpy as np

import pyabc_tpu as pt
from pyabc_tpu.external import ExternalModel

POP = int(os.environ.get("EX_POP", 120))
GENS = int(os.environ.get("EX_GENS", 3))

SCRIPT = """#!/bin/sh
# file contract: $2 = params file ('name value' lines), $4 = output file
theta=$(grep '^theta ' "$2" | cut -d' ' -f2)
x=$(awk "BEGIN {print 2.0 * $theta}")
echo "x $x" > "$4"
"""


def main():
    with tempfile.TemporaryDirectory() as d:
        prog = os.path.join(d, "sim.sh")
        with open(prog, "w") as fh:
            fh.write(SCRIPT)
        os.chmod(prog, os.stat(prog).st_mode | stat.S_IEXEC)

        model = ExternalModel(prog, name="shell_sim")
        prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
        abc = pt.ABCSMC(model, prior, pt.PNormDistance(p=2),
                        population_size=POP,
                        eps=pt.MedianEpsilon(),
                        sampler=pt.SingleCoreSampler(), seed=13)
        abc.new("sqlite://", {"x": 1.0})  # true theta = 0.5
        history = abc.run(max_nr_populations=GENS)

        df, w = history.get_distribution()
        mu = float(np.sum(df["theta"] * w))
        print(f"posterior mean theta = {mu:.3f} (true 0.5)")
        assert abs(mu - 0.5) < 0.3
        return history


if __name__ == "__main__":
    main()
