"""Every temperature scheme fused on device: Daly, Ess, fixed ladders.

Round-3 capability tour: ALL of the reference's temperature schemes now
have in-kernel device twins, so noisy ABC chains whole generations on
device regardless of the annealing strategy — DalyScheme's contraction
state and EssScheme's relative-ESS bisection run inside the fused chunk,
and a user-pinned ListTemperature ladder rides the chunk as a
precomputed schedule. The three runs below recover the same exact
posterior (deterministic model + normal noise kernel => analytically
known) from three different annealing strategies.

Run: ``python examples/08_temperature_schemes.py`` (env: EX_POP).
"""
import os
import sys

# make `python examples/<name>.py` work from a repo checkout
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

import numpy as np

import pyabc_tpu as pt
from pyabc_tpu.epsilon.temperature import DalyScheme, EssScheme

POP = int(os.environ.get("EX_POP", 300))
NOISE_SD = 0.3
X_OBS = 0.8


def exact_posterior():
    var = 1.0 / (1.0 + 1.0 / NOISE_SD**2)
    return var * X_OBS / NOISE_SD**2, np.sqrt(var)


def run(eps, label, gens):
    @pt.JaxModel.from_function(["theta"], name="det")
    def model(key, theta):
        return {"x": theta[0]}

    abc = pt.ABCSMC(
        model, pt.Distribution(theta=pt.RV("norm", 0.0, 1.0)),
        pt.IndependentNormalKernel(var=[NOISE_SD**2]),
        population_size=POP, eps=eps,
        acceptor=pt.StochasticAcceptor(), seed=17, fused_generations=4,
    )
    abc.new("sqlite://", {"x": X_OBS})
    h = abc.run(max_nr_populations=gens)
    fused = h.get_telemetry(min(2, h.max_t)).get("fused_chunk")
    df, w = h.get_distribution(0, h.max_t)
    mu = float(np.sum(df["theta"] * w))
    temps = [round(abc.eps.temperatures[t], 3)
             for t in sorted(abc.eps.temperatures) if t <= h.max_t]
    print(f"{label:28s} fused={bool(fused)!s:5s} mu={mu:+.3f} "
          f"temps={temps}")
    return mu, h


def main():
    mu_true, _ = exact_posterior()
    print(f"exact posterior mean: {mu_true:+.3f}")
    results = [
        run(pt.Temperature(schemes=[DalyScheme()],
                           initial_temperature=64.0), "Daly contraction", 7),
        run(pt.Temperature(schemes=[EssScheme()],
                           initial_temperature=64.0), "Ess bisection", 8),
        run(pt.ListTemperature([32.0, 8.0, 2.0, 1.0]),
            "ListTemperature ladder", 4),
    ]
    for mu, _h in results:
        assert abs(mu - mu_true) < 0.25, (mu, mu_true)
    print("all three annealing strategies agree with the exact posterior")
    return results[-1][1]


if __name__ == "__main__":
    main()
