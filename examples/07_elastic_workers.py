"""Elastic worker farming for host-side (non-JAX) simulators.

Reference analog: the pyABC Redis setup (``abc-redis-worker`` processes
joining a running study from any machine). Here the broker is a stdlib
TCP server owned by ``ElasticSampler`` — no redis required — and workers
(`abc-worker HOST PORT`) may join and leave at ANY time, mid-generation
included. This example spawns two local worker subprocesses; on a
cluster you would start them on other machines instead.

Run: ``python examples/07_elastic_workers.py`` (env: EX_POP, EX_GENS).
"""
import os
import sys

# make `python examples/<name>.py` work from a repo checkout
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)
import subprocess

import numpy as np

import pyabc_tpu as pt

POP = int(os.environ.get("EX_POP", 80))
GENS = int(os.environ.get("EX_GENS", 3))

WORKER = ("from pyabc_tpu.broker import run_worker; "
          "import sys; run_worker('127.0.0.1', int(sys.argv[1]))")


def main():
    def sim(pars):  # any plain-Python simulator
        return {"x": pars["theta"] + 0.5 * np.random.normal()}

    model = pt.SimpleModel(sim, name="gauss")
    prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
    sampler = pt.ElasticSampler(port=0, generation_timeout=300.0)
    port = sampler.address[1]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    workers = [
        subprocess.Popen([sys.executable, "-c", WORKER, str(port)], env=env)
        for _ in range(2)
    ]
    try:
        # distributed tracing (round 8): workers piggyback their phase
        # spans on result messages; the broker offset-maps them onto this
        # tracer's timeline as worker:<id> pseudo-threads
        tracer = pt.Tracer()
        abc = pt.ABCSMC(
            model, prior, pt.PNormDistance(p=2), population_size=POP,
            eps=pt.QuantileEpsilon(initial_epsilon=1.5, alpha=0.5),
            sampler=sampler, seed=7, tracer=tracer,
        )
        abc.new("sqlite://", {"x": 1.0})
        history = abc.run(max_nr_populations=GENS)
        df, w = history.get_distribution(0, history.max_t)
        mu = float(np.sum(df["theta"] * w))
        print(f"posterior mean {mu:.3f} (conjugate exact 0.8)")
        assert abs(mu - 0.8) < 0.4
        # elastic dark-time decomposition from the merged trace
        spans = [sp.to_dict() for sp in tracer.spans()]
        gens = [d for d in spans if d["name"] == "broker.generation"]
        rep = pt.elastic_gap_attribution(
            [d for d in spans if d["name"] not in
             ("run", "setup", "generation", "sample",
              "broker.generation")],
            min(g["start"] for g in gens), max(g["end"] for g in gens),
        )
        print("attributed fraction:", rep["attributed_frac"])
        for cat, v in rep["categories"].items():
            print(f"  {cat}: {v['frac']:.3f}")
        for wid, off in sampler.broker.worker_offsets().items():
            print(f"  worker {wid}: clock offset "
                  f"{off['offset_s'] * 1e3:.3f} ms "
                  f"(±{off['uncertainty_s'] * 1e3:.3f} ms)")
        return history
    finally:
        sampler.stop()
        for p in workers:
            p.kill()


if __name__ == "__main__":
    main()
