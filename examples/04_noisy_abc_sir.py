"""Noisy ABC (exact-likelihood inference) with a stochastic acceptor.

Reference analog: the pyABC noisy/stochastic-ABC example. Instead of a
hard distance threshold, a measurement-noise kernel scores each
simulation; StochasticAcceptor accepts with probability
exp(pdf - pdf_norm)^(1/T) and the Temperature schedule anneals T -> 1,
at which point the ABC posterior is the EXACT posterior under that noise
model (no epsilon bias).

Run: ``python examples/04_noisy_abc_sir.py`` (env: EX_POP, EX_GENS).
"""
import os
import sys

# make `python examples/<name>.py` work from a repo checkout
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

import numpy as np

import pyabc_tpu as pt
from pyabc_tpu.models import sir

POP = int(os.environ.get("EX_POP", 400))
GENS = int(os.environ.get("EX_GENS", 6))


def main():
    model = sir.make_sir_model()
    prior = sir.default_prior()
    obs = sir.observed_data(seed=11)
    n_stats = sum(np.asarray(v).size for v in obs.values())

    abc = pt.ABCSMC(
        model, prior,
        pt.IndependentNormalKernel(var=[0.01] * n_stats),
        population_size=POP,
        eps=pt.Temperature(),
        acceptor=pt.StochasticAcceptor(),
        seed=5,
    )
    abc.new("sqlite://", obs)
    # the default minimum_epsilon stops when T reaches 1 (exact posterior)
    history = abc.run(max_nr_populations=GENS)

    df, w = history.get_distribution()
    for name, true in sir.TRUE_PARS.items():
        mu = float(np.sum(df[name] * w))
        print(f"  {name}: posterior mean {mu:.4f} (true {true})")
    temps = history.get_all_populations().query("t >= 0")["epsilon"]
    print("temperature trajectory:", [round(T, 2) for T in temps])
    beta = float(np.sum(df["beta"] * w))
    assert abs(beta - sir.TRUE_PARS["beta"]) < 0.25
    return history


if __name__ == "__main__":
    main()
