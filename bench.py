"""Benchmark harness — prints ONE JSON line for the driver, ALWAYS.

Metric (BASELINE.md): accepted particles/sec per SMC generation on the
Lotka-Volterra ODE config (4 params, AdaptivePNormDistance, MedianEpsilon).

Robustness contract (a bench that can die silently is not a bench):
- A walltime budget (``PYABC_TPU_BENCH_BUDGET_S``, default 300s) caps the
  run via ABCSMC's ``max_walltime`` stopping rule.
- The JSON line is emitted even on partial completion, SIGTERM/SIGINT
  (driver timeout sends SIGTERM before SIGKILL), or an exception — via a
  process-level emit-once hook fed with whatever was measured so far.
- The TPU runtime is probed in a SUBPROCESS with its own timeout first; a
  broken/hung tunnel (this round-1 failure mode) downgrades to the CPU
  platform instead of eating the whole budget.
- ``baseline_kind`` labels the baseline honestly: it is this repo's own
  scalar host path x assumed cores (``self-architecture-proxy``), because
  the reference mount is empty and there is no network (BASELINE.md).

Env knobs: PYABC_TPU_BENCH_POP (default 1000), PYABC_TPU_BENCH_GENS (46),
PYABC_TPU_BENCH_BUDGET_S (300), PYABC_TPU_BENCH_CPU=1 (force CPU platform).
"""
import atexit
import json
import os
import signal
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))

# -- emit-once machinery ------------------------------------------------------

_state = {
    "metric": "accepted_particles_per_sec_lotka_volterra",
    "value": 0.0,
    "unit": "particles/s",
    "vs_baseline": 0.0,
    "partial": True,
    "phase": "startup",
}
_emitted = False


def _emit():
    global _emitted
    if _emitted:
        return
    _emitted = True
    print(json.dumps(_state), flush=True)


def _on_signal(signum, frame):
    _state["phase"] = f"killed_by_signal_{signum}"
    _emit()
    os._exit(1)


atexit.register(_emit)
signal.signal(signal.SIGTERM, _on_signal)
signal.signal(signal.SIGINT, _on_signal)


# -- platform probe -----------------------------------------------------------

def probe_platform(timeout_s: float = 90.0) -> str:
    """Probe the default JAX backend in a subprocess; never hang the bench.

    Returns the platform name to use ('tpu'/'axon'/'cpu'). A hung or broken
    accelerator runtime (round-1: libtpu mismatch under the axon tunnel ate
    the entire bench budget) downgrades to 'cpu'.
    """
    if os.environ.get("PYABC_TPU_BENCH_CPU"):
        return "cpu"
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices()[0]; "
             "import jax.numpy as jnp; jnp.zeros(8).block_until_ready(); "
             "print(d.platform)"],
            capture_output=True, text=True, timeout=timeout_s,
        )
        if proc.returncode == 0:
            return proc.stdout.strip().splitlines()[-1]
    except subprocess.TimeoutExpired:
        pass
    return "cpu"


# -- benchmark runs -----------------------------------------------------------

def run_tpu_bench(pop_size: int, n_gens: int, budget_s: float, seed: int = 0):
    import pandas as pd

    import pyabc_tpu as pt
    from pyabc_tpu.models import lotka_volterra as lv

    model = lv.make_lv_model()
    prior = lv.default_prior()
    obs = lv.observed_data(seed=123)

    abc = pt.ABCSMC(
        model, prior,
        pt.AdaptivePNormDistance(p=2),
        population_size=pop_size,
        eps=pt.MedianEpsilon(),
        seed=seed,
        fused_generations=8,
    )
    abc.new("sqlite://", obs)
    t0 = time.time()
    h = abc.run(max_nr_populations=n_gens + 2, max_walltime=budget_s)
    total = time.time() - t0

    pops = h.get_all_populations()
    pops = pops[pops.t >= 0]
    ends = pd.to_datetime(pops["population_end_time"])
    info = dict(total_s=round(total, 2), pop_size=pop_size,
                generations_completed=int(len(pops)),
                total_sims=int(h.total_nr_simulations))

    # fused multi-generation path: per-chunk fetch-to-fetch periods are the
    # honest steady-state clock (populations of one chunk persist in a
    # burst, so end-time spacing is meaningless). Chunk 1 carries the
    # one-off XLA compile of the G-generation program — reported separately.
    # count PERSISTED generations per chunk (a chunk that stopped early has
    # fewer telemetry rows than its planned fused_chunk size)
    chunks: dict[int, tuple[int, float]] = {}
    for t in range(h.max_t + 1):
        tel = h.get_telemetry(t)
        ci = tel.get("chunk_index")
        if ci:
            g_done = chunks.get(ci, (0, 0.0))[0] + 1
            chunks[ci] = (g_done, float(tel["chunk_s"]))
    if chunks:
        info["fused_chunks"] = [
            {"gens": g, "period_s": round(s, 3)}
            for _, (g, s) in sorted(chunks.items())
        ]
        info["compile_chunk_s"] = round(chunks[min(chunks)][1], 2)
        steady = {ci: gs for ci, gs in chunks.items() if ci >= 2}
        if steady:
            gens = sum(g for g, _ in steady.values())
            secs = sum(s for _, s in steady.values())
            info["steady_state_basis"] = (
                f"{gens} generations over {len(steady)} post-compile chunks"
            )
            return pop_size * gens / max(secs, 1e-9), info
        # only the compile chunk completed: report including compile
        gens = sum(g for g, _ in chunks.values())
        secs = sum(s for _, s in chunks.values())
        info["steady_state_basis"] = "single chunk (includes compile)"
        return pop_size * gens / max(secs, 1e-9), info

    # per-generation path: end-time spacing, excluding the two compile gens
    gen_durs = [
        round((ends.iloc[i + 1] - ends.iloc[i]).total_seconds(), 2)
        for i in range(len(ends) - 1)
    ]
    info["gen_durations_s"] = gen_durs
    if len(ends) >= 1:
        info["setup_and_gen0_s"] = round(
            total - (ends.iloc[-1] - ends.iloc[0]).total_seconds(), 2
        )
    if len(ends) >= 3:
        gens = len(ends) - 2
        elapsed = (ends.iloc[-1] - ends.iloc[1]).total_seconds()
    elif len(ends) >= 1:
        # partial run: count everything (includes compile — labeled partial)
        gens = len(ends)
        elapsed = total
    else:
        return 0.0, dict(info, note="no generation completed within budget")
    pps = pop_size * gens / max(elapsed, 1e-9)
    return pps, info


def run_host_baseline(pop_size: int = 60, n_gens: int = 2, seed: int = 0,
                      assumed_cores: int = 8, budget_s: float = 120.0):
    """Reference-ARCHITECTURE throughput proxy on this machine: the scalar
    host closure path (reference-faithful simulate_one loop) via
    SingleCoreSampler, scaled by assumed_cores as an upper bound on
    MulticoreEvalParallelSampler. Replace with a real pyABC run the moment
    the reference mount/network appears (BASELINE.md).

    Runs in a SUBPROCESS with JAX_PLATFORMS=cpu: the reference is a CPU
    framework, and the scalar path issues one tiny dispatch per simulation —
    over the axon TPU tunnel each dispatch pays ~0.1s of RPC latency, which
    would understate the baseline ~25x and flatter vs_baseline dishonestly.
    """
    code = f"""
import time, numpy as np
import jax
# the axon plugin ignores JAX_PLATFORMS; pin the default device instead
jax.config.update("jax_default_device", jax.devices("cpu")[0])
import pyabc_tpu as pt
from pyabc_tpu.models import lotka_volterra as lv
model = lv.make_lv_model(); prior = lv.default_prior()
obs = lv.observed_data(seed=123)
np.random.seed({seed})
abc = pt.ABCSMC(model, prior, pt.PNormDistance(p=2),
                population_size={pop_size},
                eps=pt.QuantileEpsilon(initial_epsilon=200.0, alpha=0.5),
                sampler=pt.SingleCoreSampler())
abc.new("sqlite://", obs)
t0 = time.time()
h = abc.run(max_nr_populations={n_gens}, max_walltime={budget_s})
elapsed = time.time() - t0
print("BASELINE_PPS", {pop_size} * h.n_populations / elapsed * {assumed_cores})
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=budget_s + 120, env=env, cwd=HERE,
    )
    for line in proc.stdout.splitlines():
        if line.startswith("BASELINE_PPS"):
            return float(line.split()[1])
    raise RuntimeError(
        f"host baseline failed: rc={proc.returncode}\n{proc.stderr[-2000:]}"
    )


def main():
    budget = float(os.environ.get("PYABC_TPU_BENCH_BUDGET_S", 300))
    pop = int(os.environ.get("PYABC_TPU_BENCH_POP", 1000))
    # enough generations for >=2 post-compile fused chunks (G=8) while
    # staying clear of the deep-schedule acceptance collapse (MedianEpsilon
    # at the noise floor, t >~ 30)
    gens = int(os.environ.get("PYABC_TPU_BENCH_GENS", 23))
    t_start = time.time()

    _state["phase"] = "probe"
    platform = probe_platform()
    _state["platform"] = platform
    if platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"

    # baseline first (cached): it is cheap and makes vs_baseline meaningful
    # even if the main run is cut short
    _state["phase"] = "baseline"
    _state["baseline_kind"] = "self-architecture-proxy"
    baseline_file = os.path.join(HERE, ".baseline_pps")
    if os.path.exists(baseline_file):
        baseline = float(open(baseline_file).read().strip())
    else:
        baseline = run_host_baseline(budget_s=min(120.0, budget / 3))
        with open(baseline_file, "w") as fh:
            fh.write(str(baseline))
    _state["baseline_particles_per_sec"] = round(baseline, 1)

    _state["phase"] = "bench"
    remaining = budget - (time.time() - t_start)
    pps, info = run_tpu_bench(pop_size=pop, n_gens=gens,
                              budget_s=max(remaining, 30.0))
    _state.update(info)
    _state["value"] = round(pps, 1)
    _state["vs_baseline"] = round(pps / baseline, 2)
    _state["partial"] = info.get("generations_completed", 0) < gens
    _state["phase"] = "done"
    _emit()


if __name__ == "__main__":
    main()
