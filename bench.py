"""Benchmark harness — prints ONE JSON line for the driver, ALWAYS.

Metric (BASELINE.md): accepted particles/sec per SMC generation on the
Lotka-Volterra ODE config (4 params, AdaptivePNormDistance, MedianEpsilon).

Robustness contract (a bench that can die silently is not a bench):
- A walltime budget (``PYABC_TPU_BENCH_BUDGET_S``, default 300s) caps the
  run via ABCSMC's ``max_walltime`` stopping rule.
- The JSON line is emitted even on partial completion, SIGTERM/SIGINT
  (driver timeout sends SIGTERM before SIGKILL), or an exception — via a
  process-level emit-once hook fed with whatever was measured so far.
- The TPU runtime is probed in a SUBPROCESS with its own timeout first; a
  broken/hung tunnel (this round-1 failure mode) downgrades to the CPU
  platform instead of eating the whole budget.
- ``baseline_kind`` labels the baseline honestly: it is this repo's own
  scalar host path x assumed cores (``self-architecture-proxy``), because
  the reference mount is empty and there is no network (BASELINE.md).

Env knobs: PYABC_TPU_BENCH_POP (default 1000), PYABC_TPU_BENCH_GENS (31),
PYABC_TPU_BENCH_G (fused generations per chunk, 16),
PYABC_TPU_BENCH_BUDGET_S (300), PYABC_TPU_BENCH_CPU=1 (force CPU platform),
PYABC_TPU_BENCH_STORE_SS=1 (store per-particle sum stats in the db),
PYABC_TPU_BENCH_ELASTIC/RESILIENCE/HEALTH/DISPATCH/SERVE=0 (disable those
lanes).
"""
import atexit
import json
import os
import signal
import subprocess
import sys
import tempfile

HERE = os.path.dirname(os.path.abspath(__file__))

#: where JSONL trace artifacts land — NEVER the repo root (a 381 KB
#: worker trace once rode a commit in); override with PYABC_TPU_TRACE_DIR
TRACE_DIR = os.environ.get("PYABC_TPU_TRACE_DIR", tempfile.gettempdir())

#: the bench's single clock (pyabc_tpu.observability.SYSTEM_CLOCK unless
#: a test installed a VirtualClock first) and the span tracer every run's
#: ABCSMC shares — both resolved lazily in main() because importing
#: pyabc_tpu before the platform decision would touch JAX
CLOCK = None
TRACER = None

# -- emit-once machinery ------------------------------------------------------

_state = {
    "metric": "accepted_particles_per_sec_lotka_volterra",
    "value": 0.0,
    "unit": "particles/s",
    "vs_baseline": 0.0,
    "partial": True,
    "phase": "startup",
}
_emitted = False


def _emit():
    global _emitted
    if _emitted:
        return
    _emitted = True
    print(json.dumps(_state), flush=True)


def _on_signal(signum, frame):
    _state["phase"] = f"killed_by_signal_{signum}"
    _emit()
    os._exit(1)


atexit.register(_emit)
signal.signal(signal.SIGTERM, _on_signal)
signal.signal(signal.SIGINT, _on_signal)


# -- platform probe -----------------------------------------------------------

def probe_platform(timeout_s: float = 90.0) -> str:
    """Probe the default JAX backend in a subprocess; never hang the bench.

    Returns the platform name to use ('tpu'/'axon'/'cpu'). A hung or broken
    accelerator runtime (round-1: libtpu mismatch under the axon tunnel ate
    the entire bench budget) downgrades to 'cpu'.
    """
    if os.environ.get("PYABC_TPU_BENCH_CPU"):
        return "cpu"
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices()[0]; "
             "import jax.numpy as jnp; jnp.zeros(8).block_until_ready(); "
             "print(d.platform)"],
            capture_output=True, text=True, timeout=timeout_s,
        )
        if proc.returncode == 0:
            return proc.stdout.strip().splitlines()[-1]
    except subprocess.TimeoutExpired:
        pass
    return "cpu"


# -- benchmark runs -----------------------------------------------------------

def build_bench_run(pop_size: int, seed: int, prev_abc):
    """Per-run HOST setup: ABCSMC construction, History/sqlite DDL,
    kernel adoption. Split out of :func:`run_tpu_bench` so the spend
    loop can execute it on a setup thread while the PREVIOUS run's
    chunks still drain the device (round-6 tentpole: per-run host setup
    used to land in dark wall-clock between runs — VERDICT r5 #1b); the
    ``setup`` span attributes it either way."""
    import pyabc_tpu as pt
    from pyabc_tpu.models import lotka_volterra as lv
    from pyabc_tpu.utils.bench_defaults import DEFAULT_G

    with TRACER.span("setup", phase="bench.build_run", seed=int(seed)):
        model = lv.make_lv_model()
        prior = lv.default_prior()
        obs = lv.observed_data(seed=123)

        abc = pt.ABCSMC(
            model, prior,
            pt.AdaptivePNormDistance(p=2),
            population_size=pop_size,
            eps=pt.MedianEpsilon(),
            seed=seed,
            fused_generations=int(
                os.environ.get("PYABC_TPU_BENCH_G", DEFAULT_G)),
            # all runs share ONE tracer on the bench clock: spans from
            # every run/thread land on the same timebase as the chunk
            # events, and the coverage accountant reports the
            # attributed-wall-clock fraction (the round-5 "dark time"
            # gap) per warm run
            tracer=TRACER,
        )
        abc.drain_async = True
        abc.compute_probe = True
        # skip per-particle sumstat storage (and with it the dominant
        # share of the per-chunk device->host fetch) unless requested
        store_ss = bool(os.environ.get("PYABC_TPU_BENCH_STORE_SS"))
        abc.new("sqlite://", obs, store_sum_stats=store_ss)
        adopted = _try_adopt(abc, prev_abc)
    return abc, adopted


def _try_adopt(abc, prev_abc) -> bool:
    """Adopt the previous run's compiled kernels (identical statistical
    config across seeds) so later runs are pure steady state. Tolerant:
    a prebuilt run may race the previous run's DeviceContext creation —
    the caller re-attempts at run start."""
    if prev_abc is None:
        return False
    try:
        abc.adopt_device_context(prev_abc)
        return abc._device_ctx is not None
    except Exception:
        return False


def run_tpu_bench(pop_size: int, n_gens: int, budget_s: float, seed: int,
                  prev_abc, on_event, prebuilt=None):
    """Launch ONE benchmark run with drain_async: run() returns once the
    generation schedule is exhausted, while the final chunks' fetches
    drain on a background thread — the CALLER starts the next run
    immediately, whose compute hides this run's drain latency (the
    round-4 drain-chunk share was 1/3 of all steady windows). Per-chunk
    completion events stream to ``on_event`` on whichever thread
    processed them; join with ``abc.drain_join()`` before reading the
    History.

    ``prebuilt``: an ``(abc, adopted)`` pair from
    :func:`build_bench_run`, typically built on the setup thread while
    the previous run drained; None builds inline (seed 0, retries)."""
    if prebuilt is not None:
        abc, adopted = prebuilt
    else:
        abc, adopted = build_bench_run(pop_size, seed, prev_abc)
    if not adopted:
        # a prebuild started before the previous run created its
        # DeviceContext adopts nothing; adoption is zero-round-trip
        # (round 5), so the retry here is host-cheap
        adopted = _try_adopt(abc, prev_abc)
    abc.chunk_event_cb = on_event
    t0 = CLOCK.now()
    try:
        abc.run(max_nr_populations=n_gens + 2, max_walltime=budget_s)
    except BaseException:
        # the caller retries without this abc: settle its background
        # state so a mid-budget failure doesn't leak the history writer
        # thread / drain thread for the rest of the bench
        for cleanup in (abc.drain_join, abc.history.close):
            try:
                cleanup()
            except Exception as ce:
                # best-effort teardown, but never silent (EXC001): the
                # primary failure re-raises below, so just leave a trace
                print(f"bench: cleanup {cleanup.__name__} failed: "
                      f"{ce!r}", file=sys.stderr)
        raise
    return abc, dict(run_s_excl_drain=round(CLOCK.now() - t0, 2),
                     adopted_kernels=adopted)


def run_host_baseline(pop_size: int = 60, n_gens: int = 2, seed: int = 0,
                      assumed_cores: int = 8, budget_s: float = 120.0):
    """Reference-ARCHITECTURE throughput proxy on this machine: the scalar
    host closure path (reference-faithful simulate_one loop) via
    SingleCoreSampler, scaled by assumed_cores as an upper bound on
    MulticoreEvalParallelSampler. Replace with a real pyABC run the moment
    the reference mount/network appears (BASELINE.md).

    Runs in a SUBPROCESS with JAX_PLATFORMS=cpu: the reference is a CPU
    framework, and the scalar path issues one tiny dispatch per simulation —
    over the axon TPU tunnel each dispatch pays ~0.1s of RPC latency, which
    would understate the baseline ~25x and flatter vs_baseline dishonestly.
    """
    code = f"""
import time, numpy as np
import jax
# the axon plugin ignores JAX_PLATFORMS; pin the default device instead
jax.config.update("jax_default_device", jax.devices("cpu")[0])
import pyabc_tpu as pt
from pyabc_tpu.models import lotka_volterra as lv
model = lv.make_lv_model(); prior = lv.default_prior()
obs = lv.observed_data(seed=123)
np.random.seed({seed})
abc = pt.ABCSMC(model, prior, pt.PNormDistance(p=2),
                population_size={pop_size},
                eps=pt.QuantileEpsilon(initial_epsilon=200.0, alpha=0.5),
                sampler=pt.SingleCoreSampler())
abc.new("sqlite://", obs)
t0 = time.monotonic()
h = abc.run(max_nr_populations={n_gens}, max_walltime={budget_s})
elapsed = time.monotonic() - t0
print("BASELINE_PPS", {pop_size} * h.n_populations / elapsed * {assumed_cores})
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=budget_s + 120, env=env, cwd=HERE,
    )
    for line in proc.stdout.splitlines():
        if line.startswith("BASELINE_PPS"):
            return float(line.split()[1])
    raise RuntimeError(
        f"host baseline failed: rc={proc.returncode}\n{proc.stderr[-2000:]}"
    )


# -- scale lane ---------------------------------------------------------------

def scale_lane_skip_reason(platform: str) -> str | None:
    """The `scale` lane targets the round-5 scale gap: pop-16384 fused LV
    with LocalTransition ran at 800-4000 pps vs the 143.7k pps headline.
    It needs a real accelerator — a pop-16k LV generation on this image's
    1-core CPU costs more than the whole bench budget — so on CPU it is
    SKIPPED WITH A RECORDED REASON unless PYABC_TPU_BENCH_SCALE=1 forces
    it (PYABC_TPU_BENCH_SCALE=0 force-disables everywhere)."""
    force = os.environ.get("PYABC_TPU_BENCH_SCALE")
    if force == "0":
        return "disabled via PYABC_TPU_BENCH_SCALE=0"
    if platform == "cpu" and force != "1":
        return ("no accelerator (cpu platform): pop-16384 LV exceeds the "
                "budget on CPU; CPU proxy = profile_gen.py --profile-refit")
    return None


def run_scale_lane(budget_s: float) -> dict:
    """ONE pop-16384 LocalTransition(k_fraction=0.25) LV run through the
    fused loop — the amortized scale-path proposal engine's measured
    lane. Emits `accepted_particles_per_sec_lv_pop16k` with a regression
    guard against the round-5 800-4000 pps band, the per-run refit count
    from the new cadence metrics, and STANDALONE refit-vs-sample span
    timing (recorded as tracer spans, so the amortization argument is
    measured wall clock, not an assumption)."""
    import statistics

    import numpy as np

    import pyabc_tpu as pt
    from pyabc_tpu.models import lotka_volterra as lv
    from pyabc_tpu.observability import MetricsRegistry
    from pyabc_tpu.utils.bench_defaults import (
        DEFAULT_G,
        DEFAULT_SCALE_GENS,
        DEFAULT_SCALE_POP,
    )

    pop = int(os.environ.get("PYABC_TPU_BENCH_SCALE_POP",
                             DEFAULT_SCALE_POP))
    gens = int(os.environ.get("PYABC_TPU_BENCH_SCALE_GENS",
                              DEFAULT_SCALE_GENS))
    reg = MetricsRegistry(clock=CLOCK)
    events: list[dict] = []
    with TRACER.span("setup", phase="bench.scale.build_run"):
        model = lv.make_lv_model()
        prior = lv.default_prior()
        obs = lv.observed_data(seed=123)
        abc = pt.ABCSMC(
            model, prior, pt.AdaptivePNormDistance(p=2),
            population_size=pop, eps=pt.MedianEpsilon(), seed=101,
            transitions=pt.LocalTransition(k_fraction=0.25),
            fused_generations=int(
                os.environ.get("PYABC_TPU_BENCH_G", DEFAULT_G)),
            tracer=TRACER, metrics=reg,
        )
        abc.new("sqlite://", obs, store_sum_stats=False)
    abc.chunk_event_cb = events.append
    t0 = CLOCK.now()
    abc.run(max_nr_populations=gens, max_walltime=max(budget_s, 30.0))
    run_s = CLOCK.now() - t0

    out = {
        "metric": "accepted_particles_per_sec_lv_pop16k",
        "unit": "particles/s",
        "pop_size": pop,
        "run_s": round(run_s, 2),
        "generations_completed": sum(e["gens"] for e in events),
    }
    # pipeline-full span basis, same as the headline: post-fill chunks /
    # span from the fill chunk's completion (one run, compile excluded
    # by construction of the span)
    fill = next((e for e in events if e["chunk_index"] == 1), None)
    rest = [e for e in events if e["chunk_index"] >= 2]
    if fill is not None and rest:
        span = max(e["ts"] for e in rest) - fill["ts"]
        value = sum(e["n_acc"] for e in rest) / max(span, 1e-9)
        out["basis"] = "pipeline-full span (post-fill chunks)"
    else:
        value = sum(e["n_acc"] for e in events) / max(run_s, 1e-9)
        out["basis"] = "whole run incl compile (too short for fill split)"
    out["value"] = round(value, 1)
    # regression guard vs the round-5 measured band: the lane must never
    # fall back INTO the band, and the tentpole target is >= 10x its top
    out["regression_guard"] = {
        "r5_band_pps": [800, 4000],
        "vs_r5_band_top_x": round(value / 4000.0, 2),
        "pass_not_regressed": bool(value >= 4000.0),
        "target_10x_band_top_pps": 40000,
        "pass_10x_band_top": bool(value >= 40000.0),
    }
    # refit-cadence accounting from the new metrics/chunk events
    snap = reg.snapshot()
    refits = [e.get("refits", 0) for e in events]
    drifts = [e["drift_last"] for e in events if "drift_last" in e]
    out["util"] = {
        "refits_per_run": int(snap.get("pyabc_tpu_refits_total", 0.0)),
        "refit_rows_changed_total": int(
            snap.get("pyabc_tpu_refit_rows_changed_total", 0.0)),
        "refits_per_chunk_median": (
            int(statistics.median(refits)) if refits else 0),
        "drift_last": (round(drifts[-1], 4) if drifts else None),
        "refit_every": abc._refit_cadence_cfg(abc._fused_n_cap()),
    }
    # standalone refit-vs-sample span timing: ONE measured device_fit at
    # the lane's exact static shapes (threshold selection + cadence
    # config), vs the measured per-generation sampling period — both
    # recorded as tracer spans ("refit" standalone=True / the chunk
    # spans), so refit amortization is wall-clock-visible in the trace
    try:
        import jax
        import jax.numpy as jnp

        n_cap = abc._fused_n_cap()
        dim = prior.space.dim
        statics = dict(abc._transition_fit_statics(pop)[0])
        rng = np.random.default_rng(0)
        Xs = jnp.asarray(rng.normal(size=(n_cap, dim)), jnp.float32)
        ws = jnp.full((n_cap,), 1.0 / n_cap, jnp.float32)
        fit_fn = jax.jit(lambda X, w: pt.LocalTransition.device_fit(
            X, w, dim=dim, **statics))
        jax.block_until_ready(fit_fn(Xs, ws))  # compile outside the span
        reps = []
        for _ in range(3):
            with TRACER.span("refit", standalone=True, n=int(n_cap)):
                t_r = CLOCK.now()
                jax.block_until_ready(fit_fn(Xs, ws))
                reps.append(CLOCK.now() - t_r)
        out["util"]["refit_s_standalone"] = round(min(reps), 4)
        gens_done = max(out["generations_completed"], 1)
        out["util"]["sample_s_per_gen"] = round(run_s / gens_done, 4)
    except Exception as e:  # timing is best-effort; the lane value stands
        out["util"]["refit_timing_error"] = repr(e)[:200]
    return out


# -- elastic lane -------------------------------------------------------------

#: orchestrator spans that BLANKET their whole window (a root or a
#: sampling wait); excluded from attribution math, same rationale as the
#: fused path's exclude_names=("run",) — a blanket span would report
#: 100% attributed and hide every gap
ELASTIC_BLANKET_SPANS = ("run", "setup", "generation", "sample",
                        "broker.generation")


def elastic_lane_skip_reason() -> str | None:
    """The `elastic` lane measures worker-tracing ATTRIBUTION on the
    broker path (round 8): host-model evaluations farmed to real worker
    subprocesses, dark time decomposed into worker compute /
    serialization / broker RTT / queue wait / orchestrator poll. It is
    CPU-cheap (host model, no accelerator involved), so it runs on every
    probe unless PYABC_TPU_BENCH_ELASTIC=0 disables it."""
    if os.environ.get("PYABC_TPU_BENCH_ELASTIC") == "0":
        return "disabled via PYABC_TPU_BENCH_ELASTIC=0"
    return None


def run_elastic_lane(budget_s: float) -> dict:
    """Elastic-worker attribution lane: >=2 worker subprocesses against
    the in-process broker, the PR-8 worker spans merged onto the bench
    tracer via the per-worker clock-offset estimates, and each warm
    run's wall clock decomposed by the elastic gap accountant with a
    regression guard on the attributed fraction (>= 0.9)."""
    import numpy as np

    import pyabc_tpu as pt
    from pyabc_tpu.observability import (
        elastic_gap_attribution,
        worker_trace_spans,
        write_trace,
    )
    from pyabc_tpu.utils.bench_defaults import (
        DEFAULT_ELASTIC_GENS,
        DEFAULT_ELASTIC_POP,
        DEFAULT_ELASTIC_RUNS,
        DEFAULT_ELASTIC_SIM_DELAY_S,
        DEFAULT_ELASTIC_WORKERS,
        ELASTIC_ATTRIBUTED_FRAC_MIN,
    )

    pop = int(os.environ.get("PYABC_TPU_BENCH_ELASTIC_POP",
                             DEFAULT_ELASTIC_POP))
    gens = int(os.environ.get("PYABC_TPU_BENCH_ELASTIC_GENS",
                              DEFAULT_ELASTIC_GENS))
    n_workers = int(os.environ.get("PYABC_TPU_BENCH_ELASTIC_WORKERS",
                                   DEFAULT_ELASTIC_WORKERS))
    delay_s = DEFAULT_ELASTIC_SIM_DELAY_S
    t_lane0 = CLOCK.now()

    def sim(pars):
        import time as _t

        _t.sleep(delay_s)  # worker compute made visible on the CPU probe
        return {"x": pars["theta"] + 0.5 * np.random.normal()}

    sampler = pt.ElasticSampler(host="127.0.0.1", port=0, batch=10,
                                generation_timeout=120.0)
    port = sampler.address[1]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = HERE + os.pathsep + env.get("PYTHONPATH", "")
    worker_code = ("from pyabc_tpu.broker import run_worker; import sys; "
                   "run_worker('127.0.0.1', int(sys.argv[1]))")
    workers = [
        subprocess.Popen([sys.executable, "-c", worker_code, str(port)],
                         env=env, stdout=subprocess.DEVNULL,
                         stderr=subprocess.DEVNULL)
        for _ in range(n_workers)
    ]
    runs = []
    try:
        for i in range(DEFAULT_ELASTIC_RUNS):
            if i > 0 and CLOCK.now() - t_lane0 > budget_s * 0.8:
                break  # keep the lane inside its share
            prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
            abc = pt.ABCSMC(
                pt.SimpleModel(sim, name="gauss_elastic"), prior,
                pt.PNormDistance(p=2), population_size=pop,
                eps=pt.QuantileEpsilon(initial_epsilon=1.5, alpha=0.5),
                sampler=sampler, seed=100 + i, tracer=TRACER,
            )
            abc.new("sqlite://", {"x": 1.0})
            t0 = CLOCK.now()
            h = abc.run(max_nr_populations=gens)
            runs.append({"run": i, "t0": t0, "t1": CLOCK.now(),
                         "generations": int(h.n_populations)})
    finally:
        for p in workers:
            p.kill()
        offsets = sampler.broker.worker_offsets()
        sampler.stop()

    sdicts = [sp.to_dict() for sp in TRACER.spans()]
    work = [d for d in sdicts if d["name"] not in ELASTIC_BLANKET_SPANS]
    per_run = []
    # run 0 is warm-up (worker subprocess startup: jax/numpy imports
    # dominate its window); runs >= 1 carry the regression guard
    for r in runs:
        rep = elastic_gap_attribution(work, r["t0"], r["t1"])
        per_run.append({
            "run": r["run"], "warm": r["run"] >= 1,
            "window_s": rep["window_s"],
            "steady_attributed_frac": rep["attributed_frac"],
            "dark_s": rep["dark_s"],
            "worker_compute_frac":
                rep["categories"]["worker_compute"]["frac"],
            "serialization_frac":
                rep["categories"]["serialization"]["frac"],
            "broker_rtt_frac": rep["categories"]["broker_rtt"]["frac"],
            "queue_wait_frac": rep["categories"]["queue_wait"]["frac"],
            "orchestrator_poll_frac":
                rep["categories"]["orchestrator_poll"]["frac"],
        })
    warm = [r for r in per_run if r["warm"]]
    # per-run worker trace JSONL export (merged spans, offset-mapped)
    trace_path = os.path.join(TRACE_DIR, ".elastic_worker_trace.jsonl")
    try:
        if os.path.exists(trace_path):
            os.remove(trace_path)
        n_exported = write_trace(trace_path, worker_trace_spans(sdicts))
    except OSError:
        trace_path, n_exported = None, 0
    out = {
        "metric": "elastic_steady_attributed_frac",
        "n_workers": n_workers, "pop_size": pop,
        "lane_s": round(CLOCK.now() - t_lane0, 2),
        "per_run": per_run,
        "gap_attribution": {
            "basis": (
                "elastic_gap_attribution over each run window: union of "
                "offset-mapped worker phase spans (per-worker pseudo-"
                "threads) + orchestrator work spans, blanket spans "
                "excluded; categories overlap so fracs need not sum to 1"
            ),
            "blanket_spans_excluded": list(ELASTIC_BLANKET_SPANS),
        },
        "workers": {
            "clock_offsets": offsets,
            "merge_uncertainty_max_s": max(
                (v["uncertainty_s"] for v in offsets.values()
                 if v.get("uncertainty_s") is not None), default=None,
            ),
        },
        "worker_trace_jsonl": {"path": trace_path, "n_spans": n_exported},
    }
    if warm:
        vals = [r["steady_attributed_frac"] for r in warm]
        out["value"] = min(vals)
        out["regression_guard"] = {
            "attributed_frac_min": ELASTIC_ATTRIBUTED_FRAC_MIN,
            "warm_run_fracs": vals,
            "pass_attributed": bool(
                min(vals) >= ELASTIC_ATTRIBUTED_FRAC_MIN),
        }
    else:
        out["value"] = 0.0
        out["regression_guard"] = {"error": "no warm run completed"}
    return out


# -- resilience lane ----------------------------------------------------------


def resilience_lane_skip_reason() -> str | None:
    """The `resilience` lane proves SELF-HEALING on every probe: an
    elastic run with one worker hard-killed mid-batch every generation
    must complete without TimeoutError, redispatch the dead worker's
    leased batches to the survivor, and keep the warm-run attributed
    fraction >= 0.9 with the recovery windows accounted (round 9). It is
    CPU-cheap like the elastic lane; PYABC_TPU_BENCH_RESILIENCE=0
    disables it."""
    if os.environ.get("PYABC_TPU_BENCH_RESILIENCE") == "0":
        return "disabled via PYABC_TPU_BENCH_RESILIENCE=0"
    return None


def run_resilience_lane(budget_s: float) -> dict:
    """Fault-injected elastic lane: one immortal worker + one MORTAL
    worker whose fault plan kills it hard after a few batches (a
    babysitter respawns it, so every generation sees at least one
    mid-batch death). Guards: completion, >= 1 redispatched batch, no
    double-counting (dedup counters), and warm-run
    ``steady_attributed_frac >= 0.9`` through ``elastic_gap_attribution``
    — now including the ``recovery`` category (orphaned->redispatched
    windows)."""
    import threading
    import time

    import numpy as np

    import pyabc_tpu as pt
    from pyabc_tpu.observability import elastic_gap_attribution
    from pyabc_tpu.utils.bench_defaults import (
        DEFAULT_RESILIENCE_GENS,
        DEFAULT_RESILIENCE_KILL_AFTER_BATCHES,
        DEFAULT_RESILIENCE_LEASE_TIMEOUT_S,
        DEFAULT_RESILIENCE_POP,
        DEFAULT_RESILIENCE_RUNS,
        DEFAULT_RESILIENCE_SIM_DELAY_S,
        RESILIENCE_ATTRIBUTED_FRAC_MIN,
    )

    pop = int(os.environ.get("PYABC_TPU_BENCH_RESILIENCE_POP",
                             DEFAULT_RESILIENCE_POP))
    gens = int(os.environ.get("PYABC_TPU_BENCH_RESILIENCE_GENS",
                              DEFAULT_RESILIENCE_GENS))
    kill_after = int(os.environ.get(
        "PYABC_TPU_BENCH_RESILIENCE_KILL_AFTER",
        DEFAULT_RESILIENCE_KILL_AFTER_BATCHES))
    delay_s = DEFAULT_RESILIENCE_SIM_DELAY_S
    t_lane0 = CLOCK.now()

    def sim(pars):
        import time as _t

        _t.sleep(delay_s)
        return {"x": pars["theta"] + 0.5 * np.random.normal()}

    # wait_for_all is the mode a dead worker used to STALL (every
    # handed-out slot must be delivered) — with leases, the kill's
    # abandoned batch REQUEUES and the survivor finishes the generation,
    # so the redispatch guard measures the healing that actually gates
    # completion, not an incidental optimization
    sampler = pt.ElasticSampler(
        host="127.0.0.1", port=0, batch=10, generation_timeout=60.0,
        wait_for_all_samples=True,
        lease_timeout_s=DEFAULT_RESILIENCE_LEASE_TIMEOUT_S,
    )
    port = sampler.address[1]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = HERE + os.pathsep + env.get("PYTHONPATH", "")
    immortal_code = (
        "from pyabc_tpu.broker import run_worker; import sys; "
        "run_worker('127.0.0.1', int(sys.argv[1]), worker_id='steady')"
    )
    # the mortal worker: its own fault plan kills it HARD (no bye, no
    # flush) after `kill_after` batches of each life
    mortal_code = (
        "from pyabc_tpu.broker import run_worker; import sys; "
        "run_worker('127.0.0.1', int(sys.argv[1]), "
        "worker_id='mortal-' + sys.argv[2], "
        f"fault_plan='worker.batch:kill:after={kill_after},max_fires=1')"
    )

    def _spawn(code, *args):
        return subprocess.Popen(
            [sys.executable, "-c", code, str(port), *args], env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )

    immortal = _spawn(immortal_code)
    lane_live = {"on": True}
    respawns = {"n": 0}

    def _babysit():
        life = 0
        proc = _spawn(mortal_code, str(life))
        while lane_live["on"]:
            if proc.poll() is not None:
                # the fault plan fired and the worker died mid-batch;
                # respawn a fresh life (fresh plan counters)
                life += 1
                respawns["n"] += 1
                proc = _spawn(mortal_code, str(life))
            time.sleep(0.2)
        proc.kill()

    babysitter = threading.Thread(target=_babysit, daemon=True)
    babysitter.start()
    runs = []
    error = None
    try:
        for i in range(DEFAULT_RESILIENCE_RUNS):
            if i > 0 and CLOCK.now() - t_lane0 > budget_s * 0.8:
                break
            prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
            abc = pt.ABCSMC(
                pt.SimpleModel(sim, name="gauss_resilience"), prior,
                pt.PNormDistance(p=2), population_size=pop,
                eps=pt.QuantileEpsilon(initial_epsilon=1.5, alpha=0.5),
                sampler=sampler, seed=200 + i, tracer=TRACER,
            )
            abc.new("sqlite://", {"x": 1.0})
            t0 = CLOCK.now()
            h = abc.run(max_nr_populations=gens)
            runs.append({"run": i, "t0": t0, "t1": CLOCK.now(),
                         "generations": int(h.n_populations)})
    except Exception as e:  # completion IS the guard — record, don't hide
        error = repr(e)[:300]
    finally:
        lane_live["on"] = False
        babysitter.join(timeout=5)
        immortal.kill()
        status = sampler.broker.status()
        sampler.stop()

    sdicts = [sp.to_dict() for sp in TRACER.spans()]
    work = [d for d in sdicts if d["name"] not in ELASTIC_BLANKET_SPANS]
    per_run = []
    for r in runs:
        rep = elastic_gap_attribution(work, r["t0"], r["t1"])
        per_run.append({
            "run": r["run"], "warm": r["run"] >= 1,
            "window_s": rep["window_s"],
            "steady_attributed_frac": rep["attributed_frac"],
            "dark_s": rep["dark_s"],
            "worker_compute_frac":
                rep["categories"]["worker_compute"]["frac"],
            "queue_wait_frac": rep["categories"]["queue_wait"]["frac"],
            "recovery_frac": rep["categories"]["recovery"]["frac"],
            "recovery_s": rep["categories"]["recovery"]["s"],
        })
    warm = [r for r in per_run if r["warm"]]
    leases = status.leases or {}
    # round-10 satellite: ZERO unattributed recovery time. The broker's
    # own recovery log is ground truth for the injected stall windows
    # (each redispatch entry carries its redispatch instant and the
    # orphaned duration); the TRACER's recovery spans — what the gap
    # accountant actually credits — must cover those windows within
    # tolerance, or the lane fails: a regression in recovery-span
    # recording would otherwise just read as slightly darker dark time.
    from pyabc_tpu.observability import interval_intersection, \
        interval_union
    from pyabc_tpu.utils.bench_defaults import (
        RESILIENCE_RECOVERY_UNATTRIBUTED_ABS_S,
        RESILIENCE_RECOVERY_UNATTRIBUTED_FRAC_MAX,
    )

    stall_ivs = [
        (ev["ts"] - ev["orphaned_s"], ev["ts"])
        for ev in (status.recovery or [])
        if ev.get("action") == "redispatch" and ev.get("orphaned_s")
    ]
    recovery_ivs = [
        (d["start"], d["end"]) for d in sdicts
        if str(d["name"]).startswith(("recovery.", "health."))
        and d.get("end") is not None
    ]
    stall_s = interval_union(stall_ivs)
    covered_s = interval_intersection(stall_ivs, recovery_ivs)
    unattributed_s = max(stall_s - covered_s, 0.0)
    recovery_accounting = {
        "stall_windows": len(stall_ivs),
        "stall_s": round(stall_s, 6),
        "covered_by_recovery_spans_s": round(covered_s, 6),
        "unattributed_s": round(unattributed_s, 6),
        "basis": (
            "broker recovery-log redispatch entries (ts - orphaned_s ->"
            " ts) vs the union of recovery.*/health.* tracer spans"
        ),
    }
    out = {
        "metric": "resilience_steady_attributed_frac",
        "pop_size": pop, "kill_after_batches": kill_after,
        "lane_s": round(CLOCK.now() - t_lane0, 2),
        "per_run": per_run,
        "worker_kills_observed": respawns["n"],
        "leases": leases,
        "recovery_accounting": recovery_accounting,
        "recovery_log_tail": list(status.recovery or [])[-10:],
        "recovery_decomposition": {
            "basis": (
                "elastic_gap_attribution with the round-9 `recovery` "
                "category: union of orphaned->redispatched lease windows "
                "(recovery.redispatch spans) within each run window"
            ),
        },
    }
    if error is not None:
        out["error"] = error
    if warm:
        vals = [r["steady_attributed_frac"] for r in warm]
        out["value"] = min(vals)
    else:
        out["value"] = 0.0
    out["regression_guard"] = {
        "attributed_frac_min": RESILIENCE_ATTRIBUTED_FRAC_MIN,
        "warm_run_fracs": [r["steady_attributed_frac"] for r in warm],
        "pass_attributed": bool(
            warm and min(r["steady_attributed_frac"] for r in warm)
            >= RESILIENCE_ATTRIBUTED_FRAC_MIN),
        "pass_completed": bool(
            error is None and runs
            and all(r["generations"] >= gens for r in runs)),
        "pass_redispatched": bool(
            leases.get("redispatched_total", 0) >= 1),
        "pass_no_double_count": True,  # dedup counters below are the
        # evidence: every duplicate was DROPPED, none admitted twice
        "duplicates_dropped": leases.get("duplicates_dropped", 0),
        # round-10 satellite: recovery time is ACCOUNTED, not dark —
        # the recovery spans must cover the broker-logged stall windows
        "pass_recovery_accounted": bool(
            unattributed_s <= max(
                RESILIENCE_RECOVERY_UNATTRIBUTED_FRAC_MAX * stall_s,
                RESILIENCE_RECOVERY_UNATTRIBUTED_ABS_S)),
        "recovery_unattributed_s": round(unattributed_s, 6),
    }
    return out


# -- health lane --------------------------------------------------------------


def health_lane_skip_reason() -> str | None:
    """The `health` lane proves the round-10 numerical guards end to
    end on every probe: a fused run with an injected mid-chunk
    ``nan_poison`` carry corruption must recover to posterior parity
    with <= 1 rolled-back chunk, and health detection must add ZERO
    blocking syncs. CPU-cheap (small fused gauss config);
    PYABC_TPU_BENCH_HEALTH=0 disables it."""
    if os.environ.get("PYABC_TPU_BENCH_HEALTH") == "0":
        return "disabled via PYABC_TPU_BENCH_HEALTH=0"
    return None


def run_health_lane(budget_s: float) -> dict:
    """NaN-poison recovery lane: seed-matched fault-free vs poisoned
    fused runs. Guards: (a) the poisoned run completes, (b) exactly one
    chunk was rolled back, (c) posterior parity — BIT-identical epsilon
    trail and final-generation weighted posterior moments, because the
    rollback target is exactly the carry the clean run chained from —
    and (d) zero extra blocking syncs from the health word itself
    (SyncLedger counts equal across the two runs; the recovery
    redispatch's own fetch is reported separately)."""
    import jax
    import numpy as np

    import pyabc_tpu as pt
    from pyabc_tpu.observability import MetricsRegistry
    from pyabc_tpu.resilience import (
        FaultPlan,
        FaultRule,
        install_fault_plan,
        uninstall_fault_plan,
    )
    from pyabc_tpu.utils.bench_defaults import (
        DEFAULT_HEALTH_G,
        DEFAULT_HEALTH_GENS,
        DEFAULT_HEALTH_POP,
        HEALTH_MAX_ROLLBACKS,
    )

    pop = int(os.environ.get("PYABC_TPU_BENCH_HEALTH_POP",
                             DEFAULT_HEALTH_POP))
    gens = int(os.environ.get("PYABC_TPU_BENCH_HEALTH_GENS",
                              DEFAULT_HEALTH_GENS))
    G = int(os.environ.get("PYABC_TPU_BENCH_HEALTH_G", DEFAULT_HEALTH_G))
    t_lane0 = CLOCK.now()

    @pt.JaxModel.from_function(["theta"], name="gauss_health")
    def model(key, theta):
        return {"x": theta[0] + 0.5 * jax.random.normal(key)}

    def make(reg):
        prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
        abc = pt.ABCSMC(
            model, prior, pt.PNormDistance(p=2), population_size=pop,
            eps=pt.MedianEpsilon(), seed=300, fused_generations=G,
            tracer=TRACER, metrics=reg,
        )
        abc.new("sqlite://", {"x": 1.0})
        return abc

    reg_ref = MetricsRegistry(clock=CLOCK)
    ref = make(reg_ref)
    h_ref = ref.run(max_nr_populations=gens)
    syncs_ref = ref.sync_ledger.summary(0.0)["syncs"]

    reg = MetricsRegistry(clock=CLOCK)
    abc = make(reg)
    install_fault_plan(FaultPlan([
        FaultRule(site="device.carry", kind="nan_poison", after=1,
                  max_fires=1),
    ]))
    error = None
    try:
        h = abc.run(max_nr_populations=gens)
    except Exception as e:  # completion IS the guard — record, not hide
        h, error = None, repr(e)[:300]
    finally:
        uninstall_fault_plan()

    out = {
        "metric": "health_nan_poison_recovery",
        "pop_size": pop, "generations": gens,
        "lane_s": round(CLOCK.now() - t_lane0, 2),
        "trail": list(abc.health_supervisor.trail),
        "rollbacks": int(abc.health_supervisor.rollbacks),
        "metrics": {
            k: v for k, v in reg.snapshot().items()
            if k.startswith("pyabc_tpu_health")
            or k.startswith("pyabc_tpu_degenerate")
        },
    }
    if error is not None:
        out["error"] = error
    completed = error is None and h is not None \
        and int(h.n_populations) >= gens
    parity = False
    moment_err = None
    if completed:
        eps_ref = h_ref.get_all_populations().query(
            "t >= 0")["epsilon"].to_numpy()
        eps_fix = h.get_all_populations().query(
            "t >= 0")["epsilon"].to_numpy()
        df_r, w_r = h_ref.get_distribution(0, gens - 1)
        df_f, w_f = h.get_distribution(0, gens - 1)
        mu_r = float(np.average(df_r["theta"], weights=w_r))
        mu_f = float(np.average(df_f["theta"], weights=w_f))
        moment_err = abs(mu_r - mu_f)
        parity = bool(np.array_equal(eps_ref, eps_fix)
                      and moment_err == 0.0)
        out["posterior_mean_ref"] = round(mu_r, 6)
        out["posterior_mean_poisoned"] = round(mu_f, 6)
    # the recovery redispatch adds exactly one extra chunk fetch; the
    # DETECTION itself must add zero syncs — compare against the clean
    # run plus the rolled-back chunks
    syncs_poisoned = abc.sync_ledger.summary(0.0)["syncs"]
    out["syncs"] = {
        "reference_run": int(syncs_ref),
        "poisoned_run": int(syncs_poisoned),
        "extra": int(syncs_poisoned - syncs_ref),
        "rolled_back_chunks": int(abc.health_supervisor.rollbacks),
    }
    out["value"] = 1.0 if (completed and parity) else 0.0
    out["regression_guard"] = {
        "pass_completed": bool(completed),
        "pass_rollback_count": bool(
            1 <= abc.health_supervisor.rollbacks
            <= HEALTH_MAX_ROLLBACKS),
        "pass_posterior_parity": bool(parity),
        "posterior_mean_abs_err": moment_err,
        # detection syncs: the only extra round trips allowed are the
        # rolled-back chunks' redispatched fetches themselves
        "pass_zero_detection_syncs": bool(
            syncs_poisoned - syncs_ref
            <= abc.health_supervisor.rollbacks),
        "max_rollbacks": HEALTH_MAX_ROLLBACKS,
    }
    return out


# -- scenario lane ------------------------------------------------------------


def scenario_lane_skip_reason() -> str | None:
    """The `scenario` lane (ISSUE 15) measures the segmented
    early-reject engine on the scenario zoo — tau-leap Gillespie
    (birth-death headline + stochastic LV), the network SIR with large
    per-particle state, and K>1 model selection — each with a pps
    number; the Gillespie lane additionally runs early-reject OFF for
    the speedup guard and asserts ON/OFF posterior bit-parity plus a
    host-oracle posterior check. PYABC_TPU_BENCH_SCENARIO=0 disables."""
    if os.environ.get("PYABC_TPU_BENCH_SCENARIO") == "0":
        return "disabled via PYABC_TPU_BENCH_SCENARIO=0"
    return None


def run_scenario_lane(budget_s: float, platform: str = "cpu") -> dict:
    """Scenario-zoo lane. The headline is the Gillespie birth-death
    EARLY-REJECT contrast: one deep MedianEpsilon run per mode (ON /
    OFF), pps computed over the LATE-GENERATION WINDOW (acceptance
    <= the few-percent regime the tentpole targets — early generations
    barely retire and would dilute an honest measurement; both modes
    use the identical window). Guards:

    - ``parity_ok``: ON and OFF accepted populations BIT-identical for
      every generation (the engine's soundness contract);
    - ``speedup_ok``: late-window accepted-particles/s ON >= 2x OFF
      (armed only when the run actually reaches the low-acceptance
      window — the regime where the claim lives);
    - ``oracle_ok``: ON-run posterior mean of gen 2 within a loose
      statistical band of a host-path (SingleCoreSampler) run of the
      same config — device vs host oracle;
    - ``sync_ok``: the dispatch engine's syncs_per_run budget holds —
      the segment accounting rides the packed fetch, zero extra syncs.
    """
    import jax
    import numpy as np

    import pyabc_tpu as pt
    from pyabc_tpu.models import gillespie as g
    from pyabc_tpu.models import model_selection as msel
    from pyabc_tpu.models import sir as sir_mod
    from pyabc_tpu.utils.bench_defaults import (
        DEFAULT_SCENARIO_GENS,
        DEFAULT_SCENARIO_POP,
        DEFAULT_SCENARIO_POP_TPU,
        DEFAULT_SCENARIO_SEGS,
        SCENARIO_LATE_ACC,
        SCENARIO_SPEEDUP_MIN_X,
    )

    t_lane0 = CLOCK.now()
    cpu = platform == "cpu"
    pop = int(os.environ.get(
        "PYABC_TPU_BENCH_SCENARIO_POP",
        DEFAULT_SCENARIO_POP if cpu else DEFAULT_SCENARIO_POP_TPU))
    gens = int(os.environ.get(
        "PYABC_TPU_BENCH_SCENARIO_GENS", DEFAULT_SCENARIO_GENS))
    segments = int(os.environ.get(
        "PYABC_TPU_BENCH_SCENARIO_SEGS", DEFAULT_SCENARIO_SEGS))
    late_acc = float(os.environ.get(
        "PYABC_TPU_BENCH_SCENARIO_LATE_ACC", SCENARIO_LATE_ACC))
    G = 2  # short chunks (G=1 would disable fused chunks entirely):
    #      per-chunk walls localize the late window at 2-gen granularity

    obs = g.observed_birth_death(segments=segments)

    def build(early, seed=7):
        abc = pt.ABCSMC(
            g.make_birth_death_model(segments=segments),
            g.birth_death_prior(), pt.PNormDistance(p=2),
            population_size=pop, eps=pt.MedianEpsilon(), seed=seed,
            early_reject=early, fused_generations=G, tracer=TRACER,
        )
        abc.new("sqlite://", obs)
        return abc

    runs = {}
    for early in ("auto", False):
        events = []
        abc = build(early)
        abc.chunk_event_cb = lambda ev, es=events: es.append(dict(ev))
        t0 = CLOCK.now()
        h = abc.run(max_nr_populations=gens,
                    max_walltime=budget_s * 0.35)
        runs[early] = {"abc": abc, "h": h, "events": events,
                       "run_s": CLOCK.now() - t0}

    if not runs["auto"]["events"] or not runs[False]["events"]:
        # e.g. a config that silently fell off the fused path — the
        # lane must fail loudly, not report 0 pps as a measurement
        return {"error": "fused chunk path did not engage "
                         "(no chunk events); scenario lane needs the "
                         "multigen kernel",
                "lane_s": round(CLOCK.now() - t_lane0, 2)}
    h_on, h_off = runs["auto"]["h"], runs[False]["h"]
    gens_done = min(h_on.max_t, h_off.max_t) + 1
    parity = True
    for t in range(gens_done):
        d1, w1 = h_on.get_distribution(m=0, t=t)
        d2, w2 = h_off.get_distribution(m=0, t=t)
        parity &= (np.array_equal(np.asarray(d1), np.asarray(d2))
                   and np.array_equal(w1, w2))

    def window(run, lo, hi):
        """(wall_s, accepted) summed over the chunks FULLY inside
        generations [lo, hi] — a straddling chunk would smuggle an
        above-threshold generation's wall into the late window (chunk
        walls include their host share)."""
        wall = acc = 0.0
        for ev in run["events"]:
            t_first, n_gens = ev["t_first"], ev["gens"]
            if n_gens and t_first >= lo and t_first + n_gens - 1 <= hi:
                wall += ev["chunk_s"]
                acc += ev["n_acc"]
        return wall, acc

    # the late window: generations at/below the target acceptance in
    # BOTH runs (same generation set — parity makes the trails equal)
    late_from = None
    for t in range(gens_done):
        tel = h_off.get_telemetry(t) or {}
        if tel.get("acceptance_rate", 1.0) <= late_acc:
            late_from = t
            break
    out = {
        "metric": "accepted_particles_per_sec_gillespie_early_reject",
        "pop_size": pop, "generations": int(gens_done),
        "segments": segments, "platform": platform,
        "parity_ok": bool(parity),
        "late_acc_threshold": late_acc,
    }
    for early, label in (("auto", "on"), (False, "off")):
        wall, acc = window(runs[early], 1, gens_done - 1)  # excl. gen 0
        out[f"pps_{label}"] = round(acc / max(wall, 1e-9), 1)
        out[f"run_s_{label}"] = round(runs[early]["run_s"], 2)
    if late_from is not None and late_from < gens_done:
        for early, label in (("auto", "on"), (False, "off")):
            wall, acc = window(runs[early], late_from, gens_done - 1)
            out[f"pps_late_{label}"] = round(acc / max(wall, 1e-9), 1)
        out["late_window_from_t"] = int(late_from)
        out["speedup_late_x"] = round(
            out["pps_late_on"] / max(out["pps_late_off"], 1e-9), 2)
        out["speedup_ok"] = bool(
            out["speedup_late_x"] >= SCENARIO_SPEEDUP_MIN_X)
    else:
        out["late_window_from_t"] = None
        out["speedup_ok"] = None  # window not reached inside budget
    out["speedup_run_x"] = round(
        out["pps_on"] / max(out["pps_off"], 1e-9), 2)
    # per-chunk walls (both modes) so the window arithmetic is auditable
    for early, label in (("auto", "on"), (False, "off")):
        out[f"chunk_walls_{label}"] = [
            (int(ev["t_first"]), int(ev["gens"]),
             round(float(ev["chunk_s"]), 2))
            for ev in runs[early]["events"]
        ]
    eng = runs["auto"]["abc"]._engine
    sync_rep = eng.sync_budget_report() if eng is not None else {}
    out["sync_ok"] = bool(sync_rep.get("ok", False))
    out["syncs_per_run"] = int(sync_rep.get("syncs", -1))
    # early-reject accounting (rides the packed fetch)
    retired = seg_steps = seg_resolved = 0
    occ_last = None
    for t in range(h_on.max_t + 1):
        tel = h_on.get_telemetry(t) or {}
        retired += tel.get("retired_early", 0)
        seg_steps += tel.get("seg_steps", 0)
        seg_resolved += tel.get("seg_resolved", 0)
        occ_last = tel.get("segment_occupancy", occ_last)
    out["util"] = {
        "lanes_retired_early_total": int(retired),
        "segment_occupancy_last": occ_last,
        "avg_segments_per_resolved": round(
            seg_steps / max(seg_resolved, 1), 2),
        "sim_work_saved_frac": round(
            1.0 - seg_steps / max(seg_resolved * segments, 1), 4),
    }

    # host-oracle posterior check (small config, loose statistical band)
    if CLOCK.now() - t_lane0 < budget_s * 0.8:
        try:
            oracle_pop, oracle_gens = 96, 3
            mus = {}
            for leg in ("fused", "host_oracle"):
                abc_o = pt.ABCSMC(
                    g.make_birth_death_model(segments=segments),
                    g.birth_death_prior(), pt.PNormDistance(p=2),
                    population_size=oracle_pop, eps=pt.MedianEpsilon(),
                    seed=21, tracer=TRACER,
                    **({"sampler": pt.SingleCoreSampler()}
                       if leg == "host_oracle" else {}),
                )
                abc_o.new("sqlite://", obs)
                h_o = abc_o.run(max_nr_populations=oracle_gens)
                df, w = h_o.get_distribution(m=0, t=h_o.max_t)
                mus[leg] = np.asarray([
                    float(np.average(df[c], weights=w))
                    for c in ("log_b", "log_d")
                ])
            err = float(np.max(np.abs(mus["fused"] - mus["host_oracle"])))
            out["oracle_posterior_mean_err"] = round(err, 3)
            # prior is 2-3 units wide; two pop-96 ABC runs at gen 3
            # carry ~0.1-0.2 posterior-mean MC error each
            out["oracle_ok"] = bool(err < 0.5)
        except Exception as e:
            out["oracle_ok"] = False
            out["oracle_error"] = repr(e)[:300]
    else:
        out["oracle_ok"] = None  # budget spent before the oracle leg

    # secondary zoo lanes: pps probes (early-reject ON), small configs
    def zoo_pps(name, models, priors, observation, pop_z, gens_z,
                seg_ok=True):
        try:
            abc_z = pt.ABCSMC(
                models, priors, pt.PNormDistance(p=2),
                population_size=pop_z, eps=pt.MedianEpsilon(), seed=5,
                early_reject="auto" if seg_ok else False,
                fused_generations=gens_z, tracer=TRACER,
            )
            abc_z.new("sqlite://", observation)
            t0 = CLOCK.now()
            h_z = abc_z.run(max_nr_populations=gens_z)
            wall = CLOCK.now() - t0
            n_acc = (h_z.max_t + 1) * pop_z
            entry = {
                "pps": round(n_acc / max(wall, 1e-9), 1),
                "pop_size": pop_z, "generations": int(h_z.max_t + 1),
                "retired_early_total": int(sum(
                    (h_z.get_telemetry(t) or {}).get("retired_early", 0)
                    for t in range(h_z.max_t + 1))),
            }
            if name == "model_selection":
                probs = h_z.get_model_probabilities(h_z.max_t)
                entry["model_probs"] = [
                    round(float(probs["p"].get(m_i, 0.0)), 4)
                    for m_i in range(len(models))
                ]
            out[name] = entry
        except Exception as e:
            out[name] = {"error": repr(e)[:300]}

    if CLOCK.now() - t_lane0 < budget_s * 0.85:
        zoo_pps("stochastic_lv",
                g.make_stochastic_lv_model(segments=segments),
                g.stochastic_lv_prior(),
                g.observed_stochastic_lv(segments=segments),
                256 if cpu else 16384, 4)
    if CLOCK.now() - t_lane0 < budget_s * 0.9:
        zoo_pps("network_sir",
                sir_mod.make_network_sir_model(),
                sir_mod.network_sir_prior(),
                sir_mod.observed_network_sir(),
                256 if cpu else 16384, 4)
    if CLOCK.now() - t_lane0 < budget_s * 0.95:
        models_k, priors_k, _ts = msel.ode_family(segments=4)
        zoo_pps("model_selection", models_k, priors_k,
                msel.observed_ode_family(seed=0, segments=4),
                192 if cpu else 8192, 3)

    # learned-sumstat leg (ISSUE 20): the high-dim network SIR
    # (n_patches=8 -> S=128 raw stats/particle) with identity vs
    # LINEAR learned summaries under the device-fit plan — the packed
    # fetch ships transformed C'=2 rows instead of raw S-dim rows, so
    # the contract is fetch-bytes/particle reduction (target >= 2x at
    # n_patches >= 8) plus the accepted-pps delta at matched budget
    if CLOCK.now() - t_lane0 < budget_s * 0.96:
        try:
            # pop must exceed need = S + 2 = 130 or the gen-0 seed fit
            # never fires and the plan silently degrades to host mode;
            # alpha=1.0 keeps the float32 normal equations conditioned
            # at S=128 (1e-6 jitter is below f32 noise on a ~n-scaled
            # Gram and NaNs the solve)
            ss_pop, ss_gens, n_patches, n_obs = 256, 5, 8, 16
            obs_sir = sir_mod.observed_network_sir(
                n_patches=n_patches, n_obs=n_obs)
            legs = {}
            for leg in ("identity", "linear"):
                dist = pt.PNormDistance(p=2) if leg == "identity" else \
                    pt.PNormDistance(p=2, sumstat=pt.PredictorSumstat(
                        pt.LinearPredictor(alpha=1.0)))
                abc_s = pt.ABCSMC(
                    sir_mod.make_network_sir_model(
                        n_patches=n_patches, n_obs=n_obs),
                    sir_mod.network_sir_prior(), dist,
                    population_size=ss_pop, eps=pt.MedianEpsilon(),
                    seed=11, fused_generations=2, tracer=TRACER,
                )
                abc_s.new("sqlite://", obs_sir)
                t0 = CLOCK.now()
                h_s = abc_s.run(max_nr_populations=ss_gens)
                wall = CLOCK.now() - t0
                summ = abc_s.sync_ledger.summary()
                fetch_b = summ["bytes_by_kind"].get("chunk_fetch", 0)
                n_acc = (h_s.max_t + 1) * ss_pop
                legs[leg] = {
                    "pps": round(n_acc / max(wall, 1e-9), 1),
                    "fetch_bytes_per_particle": round(
                        fetch_b / max(n_acc, 1), 1),
                    "syncs": int(summ["syncs"]),
                    "generations": int(h_s.max_t + 1),
                }
            red = (legs["identity"]["fetch_bytes_per_particle"]
                   / max(legs["linear"]["fetch_bytes_per_particle"],
                         1e-9))
            out["sumstat"] = {
                "identity": legs["identity"],
                "linear": legs["linear"],
                "fetch_bytes_reduction_x": round(red, 2),
                "reduction_ok": bool(red >= 2.0),
                "pps_delta_x": round(
                    legs["linear"]["pps"]
                    / max(legs["identity"]["pps"], 1e-9), 2),
                "pop_size": ss_pop, "n_patches": n_patches,
                "dim_raw": n_patches * n_obs, "dim_reduced": 2,
            }
        except Exception as e:
            out["sumstat"] = {"error": repr(e)[:300]}

    # adaptive-distance early-reject leg (ISSUE 17): the moment-based
    # refit over ALL resolved lanes is a different (unbiased) estimator
    # than the classic survivor ring, so the contract is posterior
    # parity + actually-retired work, not bit-identity
    if CLOCK.now() - t_lane0 < budget_s * 0.97:
        try:
            from pyabc_tpu.distance.scale import standard_deviation

            ad_pop, ad_gens = 128, 5
            ad = {}
            for early in ("auto", False):
                abc_a = pt.ABCSMC(
                    g.make_birth_death_model(segments=segments),
                    g.birth_death_prior(),
                    pt.AdaptivePNormDistance(
                        p=2, scale_function=standard_deviation),
                    population_size=ad_pop, eps=pt.MedianEpsilon(),
                    seed=17, early_reject=early, fused_generations=2,
                    tracer=TRACER,
                )
                abc_a.new("sqlite://", obs)
                h_a = abc_a.run(max_nr_populations=ad_gens)
                df, w = h_a.get_distribution(m=0, t=h_a.max_t)
                ad[early] = {
                    "mu": np.asarray([
                        float(np.average(df[c], weights=w))
                        for c in ("log_b", "log_d")
                    ]),
                    "retired": int(sum(
                        (h_a.get_telemetry(t) or {}).get(
                            "retired_early", 0)
                        for t in range(h_a.max_t + 1))),
                }
            ad_err = float(np.max(np.abs(ad["auto"]["mu"]
                                         - ad[False]["mu"])))
            out["adaptive"] = {
                "posterior_mean_err": round(ad_err, 3),
                "parity_ok": bool(ad_err < 0.5),
                "retired_early_total": ad["auto"]["retired"],
                "pop_size": ad_pop, "generations": ad_gens,
            }
        except Exception as e:
            out["adaptive"] = {"error": repr(e)[:300]}

    out["lane_s"] = round(CLOCK.now() - t_lane0, 2)
    out["value"] = out.get("pps_late_on", out["pps_on"])
    return out


def _scenario_sharded_child() -> dict:
    """The sharded scenario leg's measured body (ISSUE 17) — runs in a
    forced-8-device subprocess with the sync budget strict. The same
    Gillespie birth-death ON/OFF contrast as the parent lane, but on
    the COMPOSED sharded+segmented kernel: each of the 8 mesh devices
    runs its own shard-local retire/refill sweep.

    Measurement shape: the parent lane's per-chunk-wall window does
    NOT survive the engine's pipelining here — chunks are dispatched
    ahead on the device-resident carry chain, so a late chunk's fetch
    wait can read ~0 while its compute hid inside an earlier chunk's
    wait (observed: 0.002 s "walls" on chunks doing tens of seconds of
    simulation). The late window is instead isolated by SEED-MATCHED
    PREFIX SUBTRACTION on fully drained runs: per mode, one cold run
    compiles, then a warm run to ``gens - 3`` and a warm run to
    ``gens`` (context-adopted, zero compile) — identical seeds make
    the prefixes identical work, so ``wall(full) - wall(prefix)`` is
    exactly the last-3-generation wall, pipelining included. Guards:

    - ``parity_ok``: ON/OFF accepted populations bit-identical;
    - ``speedup_ok``: late-window accepted-pps ON >= 1.5x OFF (the CPU
      proxy of the >=2x real-TPU target — 8 virtual devices timeshare
      one core, so the retire win competes with collective overhead a
      real chip does not pay), armed only when the window's acceptance
      actually reached the late regime;
    - ``sync_ok``: the strict per-run sync budget holds — the per-shard
      early-reject columns ride the packed fetch;
    - per-shard accounting: retired_per_shard in telemetry, the retire
      imbalance in the engine's mesh snapshot.
    """
    import jax
    import numpy as np

    import pyabc_tpu as pt
    from pyabc_tpu.models import gillespie as g
    from pyabc_tpu.observability import SYSTEM_CLOCK
    from pyabc_tpu.parallel.distributed import local_mesh
    from pyabc_tpu.utils.bench_defaults import (
        DEFAULT_SCENARIO_SEGS,
        DEFAULT_SCENARIO_SHARDED_BUDGET_S,
        DEFAULT_SCENARIO_SHARDED_GENS,
        DEFAULT_SCENARIO_SHARDED_POP,
        SCENARIO_LATE_ACC,
        SCENARIO_SHARDED_SPEEDUP_MIN_X,
    )

    clock = SYSTEM_CLOCK
    t0 = clock.now()
    budget = float(os.environ.get(
        "PYABC_TPU_BENCH_SCENARIO_SHARDED_BUDGET_S",
        DEFAULT_SCENARIO_SHARDED_BUDGET_S))
    pop = int(os.environ.get("PYABC_TPU_BENCH_SCENARIO_SHARDED_POP",
                             DEFAULT_SCENARIO_SHARDED_POP))
    gens = int(os.environ.get("PYABC_TPU_BENCH_SCENARIO_SHARDED_GENS",
                              DEFAULT_SCENARIO_SHARDED_GENS))
    segments = int(os.environ.get("PYABC_TPU_BENCH_SCENARIO_SEGS",
                                  DEFAULT_SCENARIO_SEGS))
    late_acc = float(os.environ.get(
        "PYABC_TPU_BENCH_SCENARIO_LATE_ACC", SCENARIO_LATE_ACC))
    G = 2
    late_gens = 3
    prefix = gens - late_gens
    devs = jax.devices()
    out = {"metric": "accepted_particles_per_sec_gillespie_sharded_"
                     "early_reject",
           "n_devices": len(devs), "platform": devs[0].platform,
           "pop_size": pop, "segments": segments, "generations": gens,
           "late_gens": late_gens}
    if len(devs) < 2:
        out["skipped"] = (f"{len(devs)} device(s): the sharded leg "
                          f"needs a multi-device platform")
        return out

    obs = g.observed_birth_death(segments=segments)

    def build(early, seed=7):
        abc = pt.ABCSMC(
            g.make_birth_death_model(segments=segments),
            g.birth_death_prior(), pt.PNormDistance(p=2),
            population_size=pop, eps=pt.MedianEpsilon(), seed=seed,
            early_reject=early, fused_generations=G,
            mesh=local_mesh(),
        )
        abc.new("sqlite://", obs)
        return abc

    runs = {}
    for early in ("auto", False):
        # cold run: pays the compile, short — its wall is not compared
        abc_c = build(early)
        t_r = clock.now()
        abc_c.run(max_nr_populations=G)
        cold_s = clock.now() - t_r
        legs = {}
        for label, n_pops in (("prefix", prefix), ("full", gens)):
            abc_w = build(early)
            abc_w.adopt_device_context(abc_c)
            t_r = clock.now()
            h_w = abc_w.run(max_nr_populations=n_pops,
                            max_walltime=budget * 0.3)
            legs[label] = {"abc": abc_w, "h": h_w,
                           "run_s": clock.now() - t_r}
        runs[early] = {"cold_s": cold_s, **legs}

    h_on = runs["auto"]["full"]["h"]
    h_off = runs[False]["full"]["h"]
    gens_done = min(h_on.max_t, h_off.max_t) + 1
    if gens_done < gens or runs["auto"]["prefix"]["h"].max_t + 1 < prefix:
        out["error"] = (f"walltime cut the runs short "
                        f"({gens_done}/{gens} generations) — late-window "
                        f"subtraction needs fully drained runs; raise "
                        f"the leg budget or lower the pop")
        return out
    parity = True
    for t in range(gens_done):
        d1, w1 = h_on.get_distribution(m=0, t=t)
        d2, w2 = h_off.get_distribution(m=0, t=t)
        parity &= (np.array_equal(np.asarray(d1), np.asarray(d2))
                   and np.array_equal(w1, w2))
    out["parity_ok"] = bool(parity)

    # acceptance actually reached the late regime inside the window?
    acc_window = [
        (h_off.get_telemetry(t) or {}).get("acceptance_rate")
        for t in range(prefix, gens)
    ]
    out["late_window_acceptance"] = [
        round(a, 4) if a is not None else None for a in acc_window
    ]
    late_reached = any(a is not None and a <= late_acc
                       for a in acc_window)
    for early, label in (("auto", "on"), (False, "off")):
        r = runs[early]
        late_wall = r["full"]["run_s"] - r["prefix"]["run_s"]
        out[f"run_s_{label}"] = round(r["full"]["run_s"], 2)
        out[f"prefix_s_{label}"] = round(r["prefix"]["run_s"], 2)
        out[f"cold_s_{label}"] = round(r["cold_s"], 2)
        out[f"late_wall_s_{label}"] = round(late_wall, 2)
        out[f"pps_{label}"] = round(
            pop * gens / max(r["full"]["run_s"], 1e-9), 1)
        out[f"pps_late_{label}"] = round(
            pop * late_gens / max(late_wall, 1e-9), 1)
    out["speedup_run_x"] = round(
        out["pps_on"] / max(out["pps_off"], 1e-9), 2)
    out["speedup_late_x"] = round(
        out["pps_late_on"] / max(out["pps_late_off"], 1e-9), 2)
    out["late_regime_reached"] = bool(late_reached)
    out["speedup_ok"] = (bool(
        out["speedup_late_x"] >= SCENARIO_SHARDED_SPEEDUP_MIN_X)
        if late_reached else None)

    eng = runs["auto"]["full"]["abc"]._engine
    rep = eng.sync_budget_report() if eng is not None else {}
    out["sync_ok"] = bool(rep.get("ok", False))
    out["syncs_per_run"] = int(rep.get("syncs", -1))
    # per-shard early-reject accounting (packed fetch, zero extra syncs)
    retired = 0
    per_shard = None
    for t in range(h_on.max_t + 1):
        tel = h_on.get_telemetry(t) or {}
        retired += tel.get("retired_early", 0)
        if tel.get("retired_per_shard"):
            per_shard = tel["retired_per_shard"]
    out["lanes_retired_early_total"] = int(retired)
    out["retired_per_shard_last"] = per_shard
    mesh_block = (eng.snapshot().get("mesh") or {}) if eng else {}
    out["retire_imbalance"] = mesh_block.get("retire_imbalance")
    out["retired_per_device"] = mesh_block.get("retired_per_device")
    out["lane_s"] = round(clock.now() - t0, 2)
    out["value"] = out.get("pps_late_on", out.get("pps_on", 0.0))
    return out


def run_scenario_sharded_leg(budget_s: float,
                             platform: str = "cpu") -> dict:
    """Run the sharded scenario leg in a subprocess (forced 8 virtual
    CPU devices without an accelerator, strict sync budget armed) —
    the mesh-lane contract: a hung child never eats the bench budget.
    The floor is real: the inline lane's deep runs overshoot their
    max_walltime at chunk granularity (a late chunk can run minutes),
    so "what's left of the lane budget" is routinely near zero — a
    60s leg would always time out and record nothing."""
    budget_s = max(float(budget_s), 300.0)
    env = dict(os.environ)
    env["PYABC_TPU_BENCH_SCENARIO_SHARDED_CHILD"] = "1"
    env["PYABC_TPU_BENCH_SCENARIO_SHARDED_BUDGET_S"] = str(
        budget_s * 0.9)
    env["PYABC_TPU_SYNC_BUDGET_STRICT"] = "1"
    if platform == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(HERE, "bench.py")],
            env=env, capture_output=True, text=True, timeout=budget_s,
        )
    except subprocess.TimeoutExpired:
        return {"error": f"sharded scenario child timed out after "
                         f"{budget_s}s"}
    for line in reversed(proc.stdout.strip().splitlines() or [""]):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    return {"error": f"sharded scenario child rc={proc.returncode}: "
                     f"{(proc.stderr or '')[-400:]}"}


# -- dispatch lane ------------------------------------------------------------


def dispatch_lane_skip_reason() -> str | None:
    """The `dispatch` lane measures the round-12 engine's two invariants
    on every probe: strict wall-clock pps within 1.5x of the SAME runs'
    pipeline-full pps, and the per-run sync budget
    (syncs_per_run <= chunks + O(1)). CPU-cheap fused gauss config;
    PYABC_TPU_BENCH_DISPATCH=0 disables it."""
    if os.environ.get("PYABC_TPU_BENCH_DISPATCH") == "0":
        return "disabled via PYABC_TPU_BENCH_DISPATCH=0"
    return None


def run_dispatch_lane(budget_s: float) -> dict:
    """Dual-basis self-check on one run set: warm fused runs measured on
    BOTH bases — strict wall clock (run() entry to History complete,
    setup/calibration/gen-0/fill/drain all included) vs pipeline-full
    span (post-fill chunks over the fill-to-last-completion span). The
    two bases historically diverged ~3x (143.7k vs 45.6k pps on r5);
    the engine's speculation exists to close that, so the lane guards
    the RATIO, the engine's sync budget, and that a mid-schedule
    stopping-rule hit still rolls back >= 1 speculative chunk."""
    import jax
    import numpy as np

    import pyabc_tpu as pt
    from pyabc_tpu.observability import MetricsRegistry
    from pyabc_tpu.utils.bench_defaults import (
        DEFAULT_DISPATCH_G,
        DEFAULT_DISPATCH_GENS,
        DEFAULT_DISPATCH_POP,
        DEFAULT_DISPATCH_RUNS,
        DISPATCH_WALL_TO_PIPELINE_MIN,
    )

    pop = int(os.environ.get("PYABC_TPU_BENCH_DISPATCH_POP",
                             DEFAULT_DISPATCH_POP))
    gens = int(os.environ.get("PYABC_TPU_BENCH_DISPATCH_GENS",
                              DEFAULT_DISPATCH_GENS))
    G = int(os.environ.get("PYABC_TPU_BENCH_DISPATCH_G",
                           DEFAULT_DISPATCH_G))
    n_runs = int(os.environ.get("PYABC_TPU_BENCH_DISPATCH_RUNS",
                                DEFAULT_DISPATCH_RUNS))
    t_lane0 = CLOCK.now()

    @pt.JaxModel.from_function(["theta"], name="gauss_dispatch")
    def model(key, theta):
        return {"x": theta[0] + 0.5 * jax.random.normal(key)}

    def make(seed, reg):
        prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
        abc = pt.ABCSMC(
            model, prior, pt.PNormDistance(p=2), population_size=pop,
            eps=pt.MedianEpsilon(), seed=seed, fused_generations=G,
            tracer=TRACER, metrics=reg,
        )
        abc.new("sqlite://", {"x": 1.0})
        return abc

    prev = None
    per_run = []
    for i in range(n_runs):
        if i > 0 and CLOCK.now() - t_lane0 > budget_s * 0.85:
            break  # keep the lane inside its share (warm runs are ~1-2 s)
        reg = MetricsRegistry(clock=CLOCK)
        abc = make(400 + i, reg)
        if prev is not None:
            try:
                abc.adopt_device_context(prev)
            except Exception as e:
                print(f"dispatch lane: kernel adoption failed: {e!r}",
                      file=sys.stderr)
        events = []
        abc.chunk_event_cb = events.append
        t0 = CLOCK.now()
        h = abc.run(max_nr_populations=gens)
        wall_s = CLOCK.now() - t0  # run() returns with History complete
        n_acc = int(
            len(h.get_all_populations().query("t >= 0")) * pop)
        wall_pps = n_acc / max(wall_s, 1e-9)
        fill = next((e for e in events if e["chunk_index"] == 1), None)
        rest = [e for e in events if e["chunk_index"] >= 2]
        pipeline_pps = None
        if fill is not None and rest:
            span = max(e["ts"] for e in rest) - fill["ts"]
            if span > 0:
                pipeline_pps = sum(e["n_acc"] for e in rest) / span
        budget = abc._engine.sync_budget_report()
        per_run.append({
            "seed": 400 + i, "warm": prev is not None,
            "wall_s": round(wall_s, 3),
            "wall_clock_pps": round(wall_pps, 1),
            "pipeline_full_pps": (round(pipeline_pps, 1)
                                  if pipeline_pps else None),
            "wall_to_pipeline_ratio": (
                round(wall_pps / pipeline_pps, 4) if pipeline_pps
                else None),
            "syncs_per_run": budget["syncs"],
            "chunks": budget["chunks"],
            "sync_budget_ok": budget["ok"],
        })
        prev = abc

    # speculative-rollback probe: a mid-schedule minimum_epsilon stop
    # with the pipeline at full depth must discard >= 1 in-flight chunk
    # (History bit-identity of that rollback is tier-1-tested; here the
    # mechanism is guarded to stay EXERCISED on real configs)
    rollbacks = 0
    if prev is not None:
        eps_trail = prev.history.get_all_populations().query(
            "t >= 0")["epsilon"].to_numpy()
        if len(eps_trail) >= 4:
            reg = MetricsRegistry(clock=CLOCK)
            abc = make(400 + max(n_runs - 1, 0), reg)
            abc.adopt_device_context(prev)
            abc.run(minimum_epsilon=float(eps_trail[3]),
                    max_nr_populations=gens)
            rollbacks = int(abc._engine.speculative_rollbacks)

    warm = [r for r in per_run if r["warm"]
            and r["wall_to_pipeline_ratio"] is not None]
    ratio_min = min((r["wall_to_pipeline_ratio"] for r in warm),
                    default=None)
    out = {
        "metric": "dispatch_dual_basis_ratio",
        "pop_size": pop, "generations": gens, "fused_generations": G,
        "lane_s": round(CLOCK.now() - t_lane0, 2),
        "runs": per_run,
        "value": ratio_min if ratio_min is not None else 0.0,
        "util": {
            "syncs_per_run": (
                int(np.median([r["syncs_per_run"] for r in warm]))
                if warm else None),
            "chunks_per_run": (
                int(np.median([r["chunks"] for r in warm]))
                if warm else None),
            "speculative_rollbacks_probe": rollbacks,
        },
        "regression_guard": {
            # the tentpole acceptance: strict wall clock within 1.5x of
            # pipeline-full ON THE SAME RUN (ratio >= 1/1.5)
            "pass_wall_to_pipeline_ratio": bool(
                ratio_min is not None
                and ratio_min >= DISPATCH_WALL_TO_PIPELINE_MIN),
            "wall_to_pipeline_ratio_min": ratio_min,
            "ratio_floor": round(DISPATCH_WALL_TO_PIPELINE_MIN, 4),
            # the engine's sync budget holds on every measured run
            "pass_sync_budget": bool(
                warm and all(r["sync_budget_ok"] for r in warm)),
            # speculation is live: the stop probe rolled back in-flight
            # speculative work
            "pass_speculative_rollback": bool(rollbacks >= 1),
        },
    }
    return out


# -- mesh lane ----------------------------------------------------------------


def mesh_lane_skip_reason() -> str | None:
    """The `mesh` lane proves the sharded fused path (ISSUE 9) end to
    end: the MULTICHIP dryrun promoted to a first-class measurement —
    an 8-device sharded LV run with posterior parity, the untouched
    sync budget, and a recorded per-device pps baseline for the next
    TPU session. Runs in a SUBPROCESS (forced 8 virtual CPU devices
    when no real multi-device platform exists), so it can never
    pollute the parent bench's backend. PYABC_TPU_BENCH_MESH=0
    disables it."""
    if os.environ.get("PYABC_TPU_BENCH_MESH") == "0":
        return "disabled via PYABC_TPU_BENCH_MESH=0"
    return None


def _mesh_lane_child() -> dict:
    """The mesh lane's measured body — runs in the lane subprocess with
    the multi-device platform already configured. Three seed-matched
    runs of the SAME LV config:

    1. virtual shards (``sharded=8``, no mesh) — the lane-key-reduction
       parity reference;
    2. the real mesh run (``mesh=local_mesh()``) — must be posterior-
       IDENTICAL to (1) through the reduction, with the SyncLedger
       budget strict and the imbalance gauge recorded;
    3. the plain single-device run — statistical parity + the pps
       denominator for the scaling ratio.

    A warm mesh run (adopted kernels) supplies the headline
    ``accepted_particles_per_sec_mesh``; per-device pps is the number
    the next TPU session compares real chips against.

    Round 16 adds an ADAPTIVE leg (4): the AdaptivePNormDistance config
    the capability gate used to route onto the GSPMD fallback now runs
    the sharded kernel — its own bit-identity parity guard vs virtual
    shards, the strict sync budget (the moment-based scale reduction
    must ride existing collectives), the new cross-shard collective
    accounting, and a warm ``accepted_particles_per_sec_mesh_adaptive``
    headline.
    """
    import jax
    import numpy as np

    import pyabc_tpu as pt
    from pyabc_tpu.models import lotka_volterra as lv
    from pyabc_tpu.observability import SYSTEM_CLOCK
    from pyabc_tpu.parallel.distributed import local_mesh
    from pyabc_tpu.utils.bench_defaults import (
        DEFAULT_MESH_BUDGET_S,
        DEFAULT_MESH_G,
        DEFAULT_MESH_GENS,
        DEFAULT_MESH_POP,
    )

    clock = SYSTEM_CLOCK
    t0 = clock.now()
    budget = float(os.environ.get("PYABC_TPU_BENCH_MESH_BUDGET_S",
                                  DEFAULT_MESH_BUDGET_S))
    pop = int(os.environ.get("PYABC_TPU_BENCH_MESH_POP",
                             DEFAULT_MESH_POP))
    G = int(os.environ.get("PYABC_TPU_BENCH_MESH_G", DEFAULT_MESH_G))
    gens = int(os.environ.get("PYABC_TPU_BENCH_MESH_GENS",
                              DEFAULT_MESH_GENS))
    devs = jax.devices()
    n_dev = len(devs)
    out = {"n_devices": n_dev, "platform": devs[0].platform,
           "pop_size": pop, "fused_generations": G, "generations": gens}
    if n_dev < 2:
        out["skipped"] = (
            f"only {n_dev} device(s) available and forcing virtual "
            f"devices was unavailable on this platform")
        return out

    def make(mesh=None, sharded=None, seed=7):
        abc = pt.ABCSMC(
            lv.make_lv_model(), lv.default_prior(), pt.PNormDistance(p=2),
            population_size=pop, eps=pt.MedianEpsilon(), seed=seed,
            mesh=mesh, sharded=sharded, fused_generations=G,
        )
        abc.new("sqlite://", lv.observed_data(seed=123),
                store_sum_stats=False)
        return abc

    def run(abc, max_pops=gens):
        t_run = clock.now()
        h = abc.run(max_nr_populations=max_pops)
        wall = clock.now() - t_run
        df, w = h.get_distribution(0, h.max_t)
        post = {c: float(np.sum(df[c].to_numpy() * np.asarray(w)))
                for c in df.columns}
        eps = h.get_all_populations().query(
            "t >= 0")["epsilon"].to_numpy()
        return h, wall, post, eps

    # (1) the parity reference: the same reduction on one device
    abc_v = make(sharded=n_dev)
    h_v, _, post_v, eps_v = run(abc_v)
    # (2) the real mesh run
    mesh = local_mesh(n_dev)
    abc_m = make(mesh=mesh)
    h_m, wall_m_cold, post_m, eps_m = run(abc_m)
    budget_rep = abc_m._engine.sync_budget_report()
    snap = abc_m._engine.snapshot()
    parity_eps = float(np.max(np.abs(eps_m - eps_v))) \
        if len(eps_m) == len(eps_v) else float("inf")
    parity_post = float(max(
        abs(post_m[k] - post_v[k]) for k in post_m))
    # warm mesh run: adopted kernels, pure steady state — the headline
    pps_mesh = None
    if clock.now() - t0 < budget * 0.7:
        abc_w = make(mesh=mesh, seed=8)
        abc_w.adopt_device_context(abc_m)
        h_w, wall_w, _, _ = run(abc_w)
        pps_mesh = pop * h_w.n_populations / max(wall_w, 1e-9)
    # (3) single-device statistical reference + scaling denominator
    pps_single = None
    post_s = None
    if clock.now() - t0 < budget * 0.85:
        abc_s = make()
        h_s, wall_s, post_s, _ = run(abc_s)
        abc_s2 = make(seed=8)
        adopted = True
        try:
            abc_s2.adopt_device_context(abc_s)
        except Exception:
            adopted = False
        if adopted:
            h_s2, wall_s2, _, _ = run(abc_s2)
            pps_single = pop * h_s2.n_populations / max(wall_s2, 1e-9)
        else:
            pps_single = pop * h_s.n_populations / max(wall_s, 1e-9)
    # (4) ADAPTIVE workload (round 16, ISSUE 12): the adaptive-distance
    # config the capability gate used to reject now runs the sharded
    # kernel — measured with its own parity contract (mesh run
    # BIT-identical to the virtual-shard run), the strict sync budget
    # (the scale reduction rides existing collectives) and its own
    # warm-pps headline for the next TPU session.
    adaptive_block = {"skipped": "budget exhausted before adaptive leg"}
    if clock.now() - t0 < budget * 0.9:
        from pyabc_tpu.distance.scale import standard_deviation

        def make_adaptive(mesh=None, sharded=None, seed=9):
            abc = pt.ABCSMC(
                lv.make_lv_model(), lv.default_prior(),
                pt.AdaptivePNormDistance(
                    p=2, scale_function=standard_deviation),
                population_size=pop, eps=pt.MedianEpsilon(), seed=seed,
                mesh=mesh, sharded=sharded, fused_generations=G,
            )
            abc.new("sqlite://", lv.observed_data(seed=123),
                    store_sum_stats=False)
            return abc

        abc_av = make_adaptive(sharded=n_dev)
        h_av, _, post_av, eps_av = run(abc_av)
        abc_am = make_adaptive(mesh=mesh)
        h_am, _, post_am, eps_am = run(abc_am)
        budget_a = abc_am._engine.sync_budget_report()
        snap_a = abc_am._engine.snapshot().get("mesh", {})
        par_eps_a = float(np.max(np.abs(eps_am - eps_av))) \
            if len(eps_am) == len(eps_av) else float("inf")
        par_post_a = float(max(
            abs(post_am[k] - post_av[k]) for k in post_am))
        pps_adaptive = None
        if clock.now() - t0 < budget:
            abc_aw = make_adaptive(mesh=mesh, seed=10)
            abc_aw.adopt_device_context(abc_am)
            h_aw, wall_aw, _, _ = run(abc_aw)
            pps_adaptive = (pop * h_aw.n_populations
                            / max(wall_aw, 1e-9))
        adaptive_block = {
            "accepted_particles_per_sec_mesh_adaptive": (
                round(pps_adaptive, 1) if pps_adaptive else None),
            "posterior_mesh": {
                k: round(v, 5) for k, v in post_am.items()},
            "parity": {
                "max_abs_eps_diff_vs_virtual_shards": par_eps_a,
                "max_abs_posterior_diff_vs_virtual_shards": par_post_a,
                "generations": int(h_am.n_populations),
            },
            "util": {
                "syncs_per_run": int(budget_a["syncs"]),
                "chunks_per_run": int(budget_a["chunks"]),
                "sync_budget_ok": bool(budget_a["ok"]),
                "row_collectives_total": snap_a.get(
                    "row_collectives_total"),
                "scale_reduction_bytes_per_gen": snap_a.get(
                    "scale_reduction_bytes_per_gen"),
            },
            "regression_guard": {
                # the round-16 contract: the ADAPTIVE mesh run is
                # bit-identical to its virtual-shard reference and the
                # scale reduction added no blocking round trips
                "pass_adaptive_parity": bool(
                    par_eps_a == 0.0 and par_post_a == 0.0
                    and h_am.n_populations == h_av.n_populations),
                "pass_adaptive_sync_budget": bool(budget_a["ok"]),
            },
        }
    out["adaptive"] = adaptive_block
    out.update({
        "accepted_particles_per_sec_mesh": (
            round(pps_mesh, 1) if pps_mesh else None),
        "accepted_particles_per_sec_per_device": (
            round(pps_mesh / n_dev, 1) if pps_mesh else None),
        "accepted_particles_per_sec_single_device": (
            round(pps_single, 1) if pps_single else None),
        # NOTE for the TPU session: on forced VIRTUAL cpu devices the 8
        # "devices" share the same cores, so this ratio measures
        # sharding overhead, not scaling; real chips are where
        # near-linear scaling is the target
        "mesh_vs_single_ratio": (
            round(pps_mesh / pps_single, 3)
            if pps_mesh and pps_single else None),
        "wall_cold_mesh_s": round(wall_m_cold, 2),
        "posterior_mesh": {k: round(v, 5) for k, v in post_m.items()},
        "posterior_single": (
            {k: round(v, 5) for k, v in post_s.items()}
            if post_s else None),
        "parity": {
            "max_abs_eps_diff_vs_virtual_shards": parity_eps,
            "max_abs_posterior_diff_vs_virtual_shards": parity_post,
            "generations": int(h_m.n_populations),
        },
        "util": {
            "syncs_per_run": int(budget_rep["syncs"]),
            "chunks_per_run": int(budget_rep["chunks"]),
            "sync_budget_ok": bool(budget_rep["ok"]),
            "imbalance": snap.get("mesh", {}).get("imbalance"),
            "rounds_per_device": snap.get("mesh", {}).get(
                "rounds_per_device"),
        },
        "regression_guard": {
            # acceptance criterion: the mesh run is posterior-identical
            # (through the lane-key reduction) to the reference
            "pass_posterior_parity": bool(
                parity_eps == 0.0 and parity_post == 0.0
                and h_m.n_populations == h_v.n_populations),
            # the row merge rides the packed fetch: budget untouched
            "pass_sync_budget": bool(budget_rep["ok"]),
            "pass_completed": bool(h_m.n_populations == gens),
        },
        "lane_s": round(clock.now() - t0, 2),
    })
    return out


def _mesh_multihost_worker(role: str) -> dict:
    """One process of the mesh lane's ``multihost`` leg (round 18).

    Role "0"/"1": join the 2-process gloo runtime over the localhost
    coordinator (``PYABC_TPU_BENCH_MESH_MH_PORT``), 4 virtual CPU
    devices per process, and run the sharded multigen kernel over the
    8-device GLOBAL mesh. Role "ref": the 1-process virtual-shard run
    of the identical config — the bit-identity reference. Returns the
    measured block the parent compares (full-History sha256 digest,
    epsilon trail, strict sync budget, warm-ish pps)."""
    if role == "ref":
        import jax
        jax.config.update("jax_platforms", "cpu")
        mesh = None
    else:
        from pyabc_tpu.parallel import distributed as dist

        port = os.environ["PYABC_TPU_BENCH_MESH_MH_PORT"]
        dist.initialize(f"127.0.0.1:{port}", num_processes=2,
                        process_id=int(role), platform="cpu",
                        num_cpu_devices=4)
        import jax

        mesh = dist.global_mesh()
    import hashlib

    import numpy as np

    import pyabc_tpu as pt
    from pyabc_tpu.observability import SYSTEM_CLOCK

    from pyabc_tpu.utils.bench_defaults import (
        DEFAULT_MESH_MH_GENS,
        DEFAULT_MESH_MH_POP,
    )

    pop = int(os.environ.get("PYABC_TPU_BENCH_MESH_MH_POP",
                             DEFAULT_MESH_MH_POP))
    gens = int(os.environ.get("PYABC_TPU_BENCH_MESH_MH_GENS",
                              DEFAULT_MESH_MH_GENS))
    noise_sd = 0.5

    @pt.JaxModel.from_function(["theta"], name="gauss_mh_bench")
    def model(key, theta):
        return {"x": theta[0] + noise_sd * jax.random.normal(key)}

    abc = pt.ABCSMC(
        model, pt.Distribution(theta=pt.RV("norm", 0.0, 1.0)),
        pt.PNormDistance(p=2), population_size=pop,
        eps=pt.MedianEpsilon(), seed=21, mesh=mesh, sharded=8,
        fused_generations=3,
    )
    abc.new("sqlite://", {"x": 1.0})
    t0 = SYSTEM_CLOCK.now()
    h = abc.run(max_nr_populations=gens)
    wall = SYSTEM_CLOCK.now() - t0
    rep = abc._engine.sync_budget_report() if abc._engine else {}
    pops = h.get_all_populations().query("t >= 0")
    dig = hashlib.sha256()
    dig.update(pops["epsilon"].to_numpy().astype(np.float64).tobytes())
    for t in pops["t"]:
        df, w = h.get_distribution(0, int(t))
        dig.update(df["theta"].to_numpy().astype(np.float64).tobytes())
        dig.update(np.asarray(w, np.float64).tobytes())
    return {
        "role": role,
        "digest": dig.hexdigest(),
        "eps": [round(float(e), 10) for e in pops["epsilon"]],
        "generations": int(h.n_populations),
        "syncs_per_run": int(rep.get("syncs", -1)),
        "chunks_per_run": int(rep.get("chunks", -1)),
        "sync_budget_ok": bool(rep.get("ok", False)),
        "wall_s": round(wall, 2),
        "accepted_particles_per_sec": round(
            pop * h.n_populations / max(wall, 1e-9), 1),
    }


def run_mesh_multihost_leg(budget_s: float) -> dict:
    """The mesh lane's ``multihost`` leg: the sharded multigen kernel
    over TWO real processes (gloo CPU collectives, localhost
    coordinator, 4 virtual devices each — the multi-host-as-multi-
    process rig the CI ``multihost`` job uses), regression-guarded
    BIT-identical to the 1-process virtual-shard reference, with the
    strict per-run sync budget holding across the process boundary
    (``syncs_per_run <= chunks + O(1)``, DCN collectives ride the
    existing chunk barriers). PYABC_TPU_BENCH_MESH_MULTIHOST=0
    disables it; =1 forces it regardless of the budget floor."""
    import socket

    budget_s = float(budget_s)
    # the rig needs ~100s wall (2 gloo interpreters compile + run, then
    # the solo reference) — under that, record a skip instead of eating
    # the whole bench budget on workers that will be killed mid-compile
    if (budget_s < 150.0
            and os.environ.get("PYABC_TPU_BENCH_MESH_MULTIHOST") != "1"):
        return {"skipped": f"budget {budget_s:.0f}s < 150s floor for the "
                           "2-process gloo rig "
                           "(PYABC_TPU_BENCH_MESH_MULTIHOST=1 forces it)"}
    budget_s = max(budget_s, 60.0)
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PYABC_TPU_SYNC_BUDGET_STRICT"] = "1"
    env["PYABC_TPU_BENCH_MESH_MH_PORT"] = str(port)

    def child(role):
        e = dict(env)
        e["PYABC_TPU_BENCH_MESH_MH_ROLE"] = role
        return subprocess.Popen(
            [sys.executable, os.path.join(HERE, "bench.py")],
            env=e, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )

    def last_json(stdout, tag):
        for line in reversed((stdout or "").strip().splitlines() or [""]):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
        return {"error": f"{tag} emitted no JSON"}

    procs = [child("0"), child("1")]
    blocks = []
    try:
        for role, p in zip(("0", "1"), procs):
            out, err = p.communicate(timeout=budget_s)
            if p.returncode != 0:
                return {"error": f"multihost worker {role} rc="
                                 f"{p.returncode}: {(err or '')[-400:]}"}
            blocks.append(last_json(out, f"worker {role}"))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        return {"error": f"multihost workers timed out after {budget_s}s"}
    try:
        ref_proc = subprocess.run(
            [sys.executable, os.path.join(HERE, "bench.py")],
            env={**env, "PYABC_TPU_BENCH_MESH_MH_ROLE": "ref"},
            capture_output=True, text=True, timeout=budget_s,
        )
    except subprocess.TimeoutExpired:
        return {"error": f"multihost ref timed out after {budget_s}s"}
    if ref_proc.returncode != 0:
        return {"error": f"multihost ref rc={ref_proc.returncode}: "
                         f"{(ref_proc.stderr or '')[-400:]}"}
    ref = last_json(ref_proc.stdout, "ref")
    w0, w1 = blocks
    bit_identical = (
        "digest" in w0
        and w0.get("digest") == w1.get("digest") == ref.get("digest"))
    return {
        "n_processes": 2,
        "devices_per_process": 4,
        "collectives": "gloo",
        "eps": w0.get("eps"),
        "accepted_particles_per_sec_multihost": w0.get(
            "accepted_particles_per_sec"),
        "wall_s": w0.get("wall_s"),
        "ref_wall_s": ref.get("wall_s"),
        "util": {
            "syncs_per_run": w0.get("syncs_per_run"),
            "chunks_per_run": w0.get("chunks_per_run"),
            "sync_budget_ok": bool(w0.get("sync_budget_ok")),
        },
        "regression_guard": {
            # round-18 acceptance: the 2-process run is BIT-identical
            # (full-History digest) to the 1-process virtual-shard run,
            # and the strict sync budget holds on both processes
            "pass_bit_identity": bool(bit_identical),
            "pass_sync_budget": bool(
                w0.get("sync_budget_ok") and w1.get("sync_budget_ok")),
        },
    }


def run_mesh_lane(budget_s: float, platform: str = "cpu") -> dict:
    """Run the mesh lane in a subprocess. On accelerator platforms the
    child sees the real devices; on CPU it forces 8 virtual devices
    (``--xla_force_host_platform_device_count``) — the same rig the
    test suite and the CI ``mesh`` job use. A hung child never eats
    the bench budget (timeout -> recorded error). On CPU a slice of the
    budget goes to the ``multihost`` leg (2-process gloo rig)."""
    budget_s = max(float(budget_s), 60.0)
    mh_enabled = (platform == "cpu"
                  and os.environ.get("PYABC_TPU_BENCH_MESH_MULTIHOST")
                  != "0")
    mh_share = 0.35 if mh_enabled else 0.0
    child_budget = budget_s * (1.0 - mh_share)
    env = dict(os.environ)
    env["PYABC_TPU_BENCH_MESH_CHILD"] = "1"
    env["PYABC_TPU_BENCH_MESH_BUDGET_S"] = str(child_budget * 0.9)
    # the budget is an armed invariant in the lane, not a soft warning
    env["PYABC_TPU_SYNC_BUDGET_STRICT"] = "1"
    if platform == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(HERE, "bench.py")],
            env=env, capture_output=True, text=True,
            timeout=child_budget,
        )
    except subprocess.TimeoutExpired:
        return {"error": f"mesh lane child timed out after "
                         f"{child_budget}s"}
    out = None
    for line in reversed(proc.stdout.strip().splitlines() or [""]):
        try:
            out = json.loads(line)
            break
        except json.JSONDecodeError:
            continue
    if out is None:
        return {"error": f"mesh lane child rc={proc.returncode}: "
                         f"{(proc.stderr or '')[-400:]}"}
    if mh_enabled:
        try:
            out["multihost"] = run_mesh_multihost_leg(budget_s * mh_share)
        except Exception as e:
            out["multihost"] = {"error": repr(e)[:300]}
    return out


# -- serve lane ---------------------------------------------------------------


def serve_lane_skip_reason() -> str | None:
    """The `serve` lane (round 15: mesh-aware serving) proves the
    topology-aware scheduler end to end on a forced-8-device mesh: a
    MIXED fleet (a width-4 sharded tenant + width-1 unsharded tenants)
    with one checkpoint-PREEMPTION and one injected ``device_lost``
    event must complete with every posterior bit-identical to its
    seed-matched solo run, the preempted tenant resuming on a
    DIFFERENT-width sub-mesh, the allocator's books balancing exactly
    (zero leaked/overlapping ranges), and the round-14 fairness +
    strict-sync-budget guards still holding. Runs in a SUBPROCESS
    (forced 8 virtual CPU devices when no real multi-device platform
    exists, strict sync budget armed). The round-14 chaos-ISOLATION
    guard lives on in tier-1 (tests/test_serving.py).
    PYABC_TPU_BENCH_SERVE=0 disables it."""
    if os.environ.get("PYABC_TPU_BENCH_SERVE") == "0":
        return "disabled via PYABC_TPU_BENCH_SERVE=0"
    return None


def _serve_lane_child() -> dict:
    """The serve lane's measured body — runs in the lane subprocess
    with the 8-device platform configured and the sync budget strict.

    Timeline (one scheduler, one pool of 8 devices):

    1. solo references, no scheduler: each fleet seed's gaussian run,
       plus the big tenant's 4-shard run VIRTUALLY on one device (the
       kernel's width-independence contract makes that the bit-level
       reference for ANY sub-mesh placement);
    2. fleet A (fairness baseline): the unsharded tenants alone on the
       full pool, fault-free — per-tenant pps feeds the r14 fairness
       guard;
    3. fleet B (mixed + events): the ``sharded=4`` big tenant (leases a
       width-4 sub-mesh) + the same unsharded tenants. Once the big
       tenant has checkpointed chunks, it is checkpoint-PREEMPTED
       (event 1), requeues, resumes; then ``device_lost`` for devices
       0-5 is injected at the polled ``device.mesh`` site (event 2) —
       leases touching them reap, capacity shrinks 8 -> 2, and every
       affected tenant (budget untouched) re-places on the surviving
       width-2 block. Everything must still complete bit-identical.
    """
    import tempfile
    import time as _time

    import jax
    import numpy as np

    import pyabc_tpu as pt
    from pyabc_tpu.observability import SYSTEM_CLOCK
    from pyabc_tpu.resilience import (
        FaultPlan,
        install_fault_plan,
        uninstall_fault_plan,
    )
    from pyabc_tpu.serving import COMPLETED, RunScheduler, TenantSpec
    from pyabc_tpu.serving.tenant import _build_gaussian
    from pyabc_tpu.storage import History
    from pyabc_tpu.utils.bench_defaults import (
        DEFAULT_SERVE_BIG_GENS,
        DEFAULT_SERVE_BIG_POP,
        DEFAULT_SERVE_BUDGET_S,
        DEFAULT_SERVE_GENS,
        DEFAULT_SERVE_POP,
        DEFAULT_SERVE_TENANTS,
        SERVE_FAIRNESS_MAX_RATIO,
    )

    clock = SYSTEM_CLOCK
    t0 = clock.now()
    budget = float(os.environ.get("PYABC_TPU_BENCH_SERVE_BUDGET_S",
                                  DEFAULT_SERVE_BUDGET_S))
    n_small = int(os.environ.get("PYABC_TPU_BENCH_SERVE_TENANTS",
                                 DEFAULT_SERVE_TENANTS))
    pop = int(os.environ.get("PYABC_TPU_BENCH_SERVE_POP",
                             DEFAULT_SERVE_POP))
    gens = int(os.environ.get("PYABC_TPU_BENCH_SERVE_GENS",
                              DEFAULT_SERVE_GENS))
    big_pop = int(os.environ.get("PYABC_TPU_BENCH_SERVE_BIG_POP",
                                 DEFAULT_SERVE_BIG_POP))
    big_gens = int(os.environ.get("PYABC_TPU_BENCH_SERVE_BIG_GENS",
                                  DEFAULT_SERVE_BIG_GENS))
    G = 2
    n_dev = len(jax.devices())
    out = {"n_devices": n_dev, "n_small_tenants": n_small,
           "pop_size": pop, "generations": gens,
           "big": {"pop_size": big_pop, "generations": big_gens,
                   "sharded": 4}}
    if n_dev < 8:
        out["skipped"] = (
            f"only {n_dev} device(s) and forcing virtual devices was "
            f"unavailable on this platform")
        return out

    base_dir = tempfile.mkdtemp(prefix="abc-bench-serve-")
    small_seeds = [500 + i for i in range(n_small)]
    BIG_SEED = 9001

    def small_spec(seed):
        return TenantSpec(model="gaussian", population_size=pop,
                          generations=gens, seed=seed,
                          fused_generations=G)

    def history_arrays(db):
        h = History(db)
        eps = h.get_all_populations().query(
            "t >= 0")["epsilon"].to_numpy()
        arrs = [eps]
        for t in range(h.n_populations):
            df, w = h.get_distribution(0, t)
            arrs.append(np.sort(df["theta"].to_numpy()))
            arrs.append(np.sort(np.asarray(w)))
        n = h.n_populations
        h.close()
        return n, arrs

    def bit_identical(db_a, db_b):
        na, a = history_arrays(db_a)
        nb, b = history_arrays(db_b)
        return (na == nb and len(a) == len(b)
                and all(np.array_equal(x, y) for x, y in zip(a, b)))

    # -- 1. solo references (these double as the shape warm-up)
    def solo(seed, db, *, pop_, gens_, sharded=None):
        built = _build_gaussian(small_spec(seed))
        observed = built.pop("observed")
        abc = pt.ABCSMC(population_size=pop_, seed=seed,
                        fused_generations=G, sharded=sharded, **built)
        abc.new(db, observed, store_sum_stats=True)
        abc.run(max_nr_populations=gens_)

    for s in small_seeds:
        solo(s, f"sqlite:///{base_dir}/ref_{s}.db", pop_=pop, gens_=gens)
    solo(BIG_SEED, f"sqlite:///{base_dir}/ref_big.db",
         pop_=big_pop, gens_=big_gens, sharded=4)

    sched = RunScheduler(
        n_devices=8, max_queued=4 * n_small + 4, lease_timeout_s=120.0,
        max_requeues=1, base_dir=base_dir,
    )

    def wait(tenants, share):
        deadline = clock.now() + max(budget * share, 30.0)
        while clock.now() < deadline:
            if all(t.state in ("completed", "failed", "cancelled",
                               "drained") for t in tenants):
                return True
            _time.sleep(0.1)
        return False

    try:
        # -- 2. fleet A: fairness baseline on the full healthy pool
        fleet_a = [sched.submit(small_spec(s), tenant_id=f"a-{i}")
                   for i, s in enumerate(small_seeds)]
        wait(fleet_a, 0.25)
        a_ok = [t for t in fleet_a if t.state == COMPLETED]
        pps = [pop * gens / t.run_s for t in a_ok if t.run_s > 0]
        fairness = (max(pps) / min(pps)) if pps else float("inf")

        # -- 3. fleet B: mixed, one preemption + one device_lost
        big = sched.submit(
            TenantSpec(model="gaussian", population_size=big_pop,
                       generations=big_gens, seed=BIG_SEED,
                       fused_generations=G, sharded=4),
            tenant_id="bigshard")
        fleet_b = [sched.submit(small_spec(s), tenant_id=f"b-{i}")
                   for i, s in enumerate(small_seeds)]
        t_ev = clock.now()
        while big.generations_done < 2 and clock.now() - t_ev < 120:
            _time.sleep(0.05)
        preempt_ack = sched.preempt("bigshard")
        t_ev = clock.now()
        while big.preemptions < 1 and clock.now() - t_ev < 120:
            _time.sleep(0.05)
        # let it resume before the mesh loss
        t_ev = clock.now()
        while big.state != "running" and clock.now() - t_ev < 120:
            _time.sleep(0.05)
        install_fault_plan(FaultPlan.parse(
            "device.mesh:device_lost:devices=0-5"))
        t_ev = clock.now()
        while sched.devices_lost_total < 6 and clock.now() - t_ev < 60:
            _time.sleep(0.05)
        uninstall_fault_plan()
        completed_in_time = wait([big] + fleet_b, 0.55)

        place = sched.allocator.stats()
        invariant_problems = sched.allocator.check_invariants()
        b_ok = [t for t in fleet_b if t.state == COMPLETED]
        parity_small = all(
            bit_identical(t.db_path,
                          f"sqlite:///{base_dir}/ref_{500 + i}.db")
            for i, t in enumerate(fleet_b) if t.state == COMPLETED)
        parity_big = (big.state == COMPLETED and bit_identical(
            big.db_path, f"sqlite:///{base_dir}/ref_big.db"))

        out.update({
            "metric": "serve_mesh_aware_mixed_fleet",
            "lane_s": round(clock.now() - t0, 2),
            "fleet_a_completed": len(a_ok),
            "fleet_b_completed": len(b_ok),
            "big_state": big.state,
            "big_widths": list(big.widths),
            "big_preemptions": int(big.preemptions),
            "big_requeue_budget_spent": int(big.requeues),
            "big_device_loss_requeues": int(big.device_loss_requeues),
            "devices_lost": int(sched.devices_lost_total),
            "healthy_devices_after": place["healthy_devices"],
            "fairness_max_min_pps_ratio": round(fairness, 4),
            "tenant_pps": [round(v, 1) for v in pps],
            "retry_after_repriced": sched.snapshot()["admission"],
            "placement": place,
            "allocator_invariant_problems": invariant_problems,
            "kernel_cache": sched.kernel_cache.stats(),
            "stale_reports_discarded": int(
                sched.stale_reports_discarded),
        })
        guard = {
            # every tenant (both fleets + the twice-displaced big one)
            # finishes with a posterior, under the STRICT sync budget
            # armed in this child's environment
            "pass_all_complete": bool(
                completed_in_time and len(a_ok) == n_small
                and len(b_ok) == n_small and big.state == COMPLETED),
            # survivors' posteriors bit-identical to solo runs
            "pass_survivor_parity": bool(parity_small),
            # the preempted tenant resumed BIT-identical on a
            # DIFFERENT-width sub-mesh (4 -> the surviving 2-wide
            # block after the mesh loss), budget untouched
            "pass_preempted_resumes_different_width": bool(
                preempt_ack and big.preemptions == 1
                and parity_big and len(big.widths) >= 2
                and big.widths[0] == 4 and big.widths[-1] < 4
                and big.requeues == 0),
            "pass_device_loss_survived": bool(
                sched.devices_lost_total == 6
                and place["healthy_devices"] == 2),
            # zero leaked/overlapping device ranges after coalescing
            "pass_allocator_books_balance": invariant_problems == [],
            # the r14 fairness guard, measured on fleet A
            "pass_fairness": bool(fairness <= SERVE_FAIRNESS_MAX_RATIO),
            "sync_budget_strict_armed": bool(
                os.environ.get("PYABC_TPU_SYNC_BUDGET_STRICT") == "1"),
        }
        out["regression_guard"] = guard
        out["value"] = 1.0 if all(
            v for k, v in guard.items() if k.startswith("pass_")
        ) else 0.0
        return out
    finally:
        sched.shutdown()


def run_serve_lane(budget_s: float) -> dict:
    """Run the serve lane in a subprocess with 8 forced virtual CPU
    devices (accelerator platforms see their real devices) and the
    sync budget STRICT — the same rig as the mesh lane and the CI
    ``serve`` job. A hung child never eats the bench budget."""
    budget_s = max(float(budget_s), 240.0)
    env = dict(os.environ)
    env["PYABC_TPU_BENCH_SERVE_CHILD"] = "1"
    env["PYABC_TPU_BENCH_SERVE_BUDGET_S"] = str(budget_s * 0.9)
    env["PYABC_TPU_SYNC_BUDGET_STRICT"] = "1"
    if probe_platform() == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(HERE, "bench.py")],
            env=env, capture_output=True, text=True, timeout=budget_s,
        )
    except subprocess.TimeoutExpired:
        return {"error": f"serve lane child timed out after {budget_s}s"}
    for line in reversed(proc.stdout.strip().splitlines() or [""]):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    return {"error": f"serve lane child rc={proc.returncode}: "
                     f"{(proc.stderr or '')[-400:]}"}


# -- storage lane -------------------------------------------------------------


def storage_lane_skip_reason() -> str | None:
    """The `storage` lane (round 17) measures History INGEST — the
    dual-basis gap at scenario-zoo scale, where the async writer (not
    the kernel) becomes the ceiling: the same pop-16384 packed-fetch
    generations appended to the row store (reference SQL layout, WAL
    on and off) and to the columnar generation-batch store (one
    Parquet record batch per generation, narrow dtypes preserved).
    Headline = columnar/row ingest ratio, regression-guarded >= 10x.
    Host-only (no jax, no device): nothing to skip except opt-out."""
    if os.environ.get("PYABC_TPU_BENCH_STORAGE") == "0":
        return "disabled via PYABC_TPU_BENCH_STORAGE=0"
    return None


def run_storage_lane(budget_s: float) -> dict:
    """Apples-to-apples History ingest: each store receives EXACTLY what
    the live fused loop hands it for a persisted generation — the row
    store a deferred-built Population (normalization included, as on the
    writer thread), the columnar store a GenerationBatch wrapping the
    raw packed-fetch slices. Synchronous appends, so the measured wall
    is pure ingest (no queue time)."""
    import tempfile

    import numpy as np

    from pyabc_tpu.core.parameters import ParameterSpace
    from pyabc_tpu.core.population import Population
    from pyabc_tpu.core.sumstat_spec import SumStatSpec
    from pyabc_tpu.sampler.base import Sample, exp_normalize_log_weights
    from pyabc_tpu.storage import GenerationBatch, History
    from pyabc_tpu.storage.columnar import has_pyarrow
    from pyabc_tpu.utils.bench_defaults import (
        DEFAULT_STORAGE_GENS,
        DEFAULT_STORAGE_GUARD_MIN_X,
        DEFAULT_STORAGE_POP,
    )

    pop = int(os.environ.get("PYABC_TPU_BENCH_STORAGE_POP",
                             DEFAULT_STORAGE_POP))
    gens = int(os.environ.get("PYABC_TPU_BENCH_STORAGE_GENS",
                              DEFAULT_STORAGE_GENS))
    d, S = 4, 8
    t_lane0 = CLOCK.now()
    rng = np.random.default_rng(1234)
    fetches = [
        {
            "ms": np.zeros(pop, np.int32),
            "thetas": rng.normal(size=(pop, d)).astype(np.float16),
            "log_weights": rng.normal(size=pop).astype(np.float16),
            "distances": np.abs(rng.normal(size=pop)).astype(np.float16),
            "sumstats": rng.normal(size=(pop, S)).astype(np.float16),
            "slots": np.arange(pop),
        }
        for _ in range(gens)
    ]
    names = [[f"p{i}" for i in range(d)]]
    spec = SumStatSpec({"x": np.zeros(S)})
    tmp = tempfile.mkdtemp(prefix="pyabc_tpu_storage_lane_")

    def _population(arrs) -> Population:
        # the row path's deferred _build: Sample normalization +
        # Population construction, charged to the ingest wall exactly
        # as it is on the live writer thread
        sample = Sample()
        sample.set_accepted(
            ms=arrs["ms"],
            thetas=np.asarray(arrs["thetas"], np.float64),
            weights=exp_normalize_log_weights(arrs["log_weights"]),
            distances=np.asarray(arrs["distances"], np.float64),
            sumstats=np.asarray(arrs["sumstats"], np.float64),
            proposal_ids=arrs["slots"],
        )
        return Population(
            ms=sample.ms, thetas=sample.thetas, weights=sample.weights,
            distances=sample.distances, sumstats=sample.sumstats,
            spaces=[ParameterSpace(n) for n in names],
            sumstat_spec=spec, model_names=["m0"],
        )

    def _ingest(db_url: str, make_payload, wal: bool = True,
                n_gens: int = gens) -> dict:
        h = History(db_url, wal=wal)
        h.store_initial_data(None, {}, {"x": np.zeros(S)}, {},
                             ["m0"], "{}", "{}", "{}")
        t0 = CLOCK.now()
        for t in range(n_gens):
            h.append_population(t, 1.0 - 0.01 * t, make_payload(fetches[t]),
                                3 * pop, ["m0"])
        wall = CLOCK.now() - t0
        on_disk = h.last_ingest["bytes_on_disk"]
        h.close()
        rows = pop * n_gens
        return {
            "rows": rows,
            "wall_s": round(wall, 4),
            "rows_per_sec": round(rows / max(wall, 1e-9), 1),
            "bytes_per_particle": round(on_disk / rows, 2),
        }

    rows_wal = _ingest(f"sqlite:///{tmp}/rows_wal.db", _population)
    # WAL satellite: same ingest with the pragmas off (fresh db,
    # rollback-journal mode) — the measured delta the pragma buys
    rows_nowal = _ingest(f"sqlite:///{tmp}/rows_nowal.db", _population,
                         wal=False, n_gens=max(gens // 2, 1))
    out = {
        "pop": pop, "gens": gens,
        "rows_store": rows_wal,
        "rows_store_no_wal": rows_nowal,
        "wal_speedup_x": round(
            rows_wal["rows_per_sec"]
            / max(rows_nowal["rows_per_sec"], 1e-9), 2),
    }
    guard_min = float(os.environ.get(
        "PYABC_TPU_BENCH_STORAGE_GUARD_MIN_X",
        DEFAULT_STORAGE_GUARD_MIN_X))
    if has_pyarrow():
        col = _ingest(
            f"sqlite+columnar:///{tmp}/col.db",
            lambda arrs: GenerationBatch.from_fetch(
                param_names=names, **arrs),
        )
        ratio = col["rows_per_sec"] / max(rows_wal["rows_per_sec"], 1e-9)
        out["columnar_store"] = col
        out["ingest_ratio_columnar_vs_rows"] = round(ratio, 2)
        # regression guard: the tentpole's acceptance line — columnar
        # ingest must stay >= 10x the row store at pop-16384 scale
        out["guard_min_ratio_x"] = guard_min
        out["guard_ok"] = bool(ratio >= guard_min)
        out["value"] = round(col["rows_per_sec"], 1)
    else:
        out["columnar_store"] = {"skipped": "pyarrow not installed"}
        out["guard_ok"] = None
        out["value"] = 0.0
    out["util"] = {
        "history_ingest_rows_per_sec_rows": rows_wal["rows_per_sec"],
        "history_ingest_rows_per_sec_columnar": (
            out.get("columnar_store", {}).get("rows_per_sec", 0.0)
            if has_pyarrow() else 0.0),
        "history_bytes_per_particle_rows": rows_wal["bytes_per_particle"],
        "history_bytes_per_particle_columnar": (
            out["columnar_store"].get("bytes_per_particle")
            if has_pyarrow() else None),
        "wal_speedup_x": out["wal_speedup_x"],
    }
    out["lane_s"] = round(CLOCK.now() - t_lane0, 2)
    return out


# -- traffic lane -------------------------------------------------------------


def traffic_lane_skip_reason() -> str | None:
    """The `traffic` lane (round 19) runs the fleet-scale churn rig: an
    open-loop seeded Poisson arrival process over the tenant-spec zoo
    against a live RunScheduler with retention/GC/quotas armed, at an
    arrival rate deliberately above what the pool can drain. Guards:
    p99 admission latency, Retry-After honesty, within-class fairness,
    and BOUNDED DISK — total History bytes stay under the fleet budget
    and disposed tenants leave no files behind (the satellite-1
    eviction-GC bugfix, measured). PYABC_TPU_BENCH_TRAFFIC=0 disables
    it."""
    if os.environ.get("PYABC_TPU_BENCH_TRAFFIC") == "0":
        return "disabled via PYABC_TPU_BENCH_TRAFFIC=0"
    return None


def _traffic_lane_child() -> dict:
    """The traffic lane's measured body — runs in the lane subprocess
    with the 8-device platform configured and the sync budget strict.

    Open-loop means the schedule does not wait for the pool: arrivals
    land on time whether or not earlier tenants finished, 429s retry
    exactly Retry-After later, and the lane's job is to measure what
    the serving stack does under that pressure — not to complete every
    tenant. Guards are ARMED only when their sample counts make them
    meaningful (the scenario-lane precedent); the disk guards are
    always armed.
    """
    import tempfile
    import time
    from pathlib import Path

    import jax

    from pyabc_tpu.observability import (
        SLO,
        SYSTEM_CLOCK,
        MetricsRegistry,
        SloEngine,
        VirtualClock,
        coverage_report,
        read_flight,
        render_timeline,
    )
    from pyabc_tpu.serving import (
        AdmissionRejectedError,
        RetentionPolicy,
        RunScheduler,
        TenantQuota,
        TenantSpec,
    )
    from pyabc_tpu.traffic import (
        ArrivalSchedule,
        TrafficClass,
        TrafficGenerator,
    )
    from pyabc_tpu.utils.bench_defaults import (
        DEFAULT_TRAFFIC_BUDGET_S,
        DEFAULT_TRAFFIC_DISK_BUDGET_BYTES,
        DEFAULT_TRAFFIC_PROFILE,
        DEFAULT_TRAFFIC_RATE_HZ,
        DEFAULT_TRAFFIC_SEED,
        DEFAULT_TRAFFIC_TENANTS,
        SLO_ALERT_LATENCY_MAX_S,
        SLO_RECORDER_ATTRIBUTED_FRAC_MIN,
        TRAFFIC_ADMIT_P99_MAX_S,
        TRAFFIC_FAIRNESS_MAX_RATIO,
        TRAFFIC_HONESTY_P90_MAX,
    )

    clock = SYSTEM_CLOCK
    t0 = clock.now()
    budget = float(os.environ.get("PYABC_TPU_BENCH_TRAFFIC_BUDGET_S",
                                  DEFAULT_TRAFFIC_BUDGET_S))
    n_tenants = int(os.environ.get("PYABC_TPU_BENCH_TRAFFIC_TENANTS",
                                   DEFAULT_TRAFFIC_TENANTS))
    rate_hz = float(os.environ.get("PYABC_TPU_BENCH_TRAFFIC_RATE_HZ",
                                   DEFAULT_TRAFFIC_RATE_HZ))
    profile = os.environ.get("PYABC_TPU_BENCH_TRAFFIC_PROFILE",
                             DEFAULT_TRAFFIC_PROFILE)
    seed = int(os.environ.get("PYABC_TPU_BENCH_TRAFFIC_SEED",
                              str(DEFAULT_TRAFFIC_SEED)))
    disk_budget = int(os.environ.get(
        "PYABC_TPU_BENCH_TRAFFIC_DISK_BUDGET_BYTES",
        DEFAULT_TRAFFIC_DISK_BUDGET_BYTES))
    n_dev = len(jax.devices())
    out = {"n_devices": n_dev, "n_tenants": n_tenants,
           "rate_hz": rate_hz, "profile": profile, "seed": seed,
           "disk_budget_bytes": disk_budget}
    if n_dev < 8:
        out["skipped"] = (
            f"only {n_dev} device(s) and forcing virtual devices was "
            f"unavailable on this platform")
        return out

    base_dir = tempfile.mkdtemp(prefix="abc-bench-traffic-")
    sched = RunScheduler(
        n_devices=8, packing=2, max_queued=16, lease_timeout_s=120.0,
        max_requeues=1, base_dir=base_dir,
        # small terminal-retention cap + 1s sweeps keep the
        # dispose/GC machinery HOT for the bounded-disk guards
        max_terminal_tenants=32, lifecycle_sweep_s=1.0,
        retention=RetentionPolicy(keep_last_k=1,
                                  total_bytes_budget=disk_budget),
        quota=TenantQuota(max_generations=64),
    )
    schedule = ArrivalSchedule.poisson(
        n_tenants, rate_hz=rate_hz, seed=seed, profile=profile)
    gen = TrafficGenerator(sched, schedule)
    try:
        # -- phase 1, DRAINED SLO PROBES on the fresh, idle pool:
        # storms sized so the pool actually drains them — the regime
        # where the Retry-After hint's promise (and per-tenant
        # treatment) is testable. Probes must run BEFORE the churn:
        # churn's cancelled stragglers burn the box until their chunk
        # boundaries (minutes, for big tenants), and a probe racing
        # them measures the backlog again — its hints price
        # straggler-occupied slots, its service times share their CPU.
        # The honesty probe is one burst over slot+queue capacity
        # (rejections guaranteed, every arrival eventually admitted);
        # the fairness probe is paced Poisson at near-constant
        # concurrency so service times compare like for like. Guards
        # arm only if the probe drained (cold-compile-bound smoke runs
        # record instead of asserting — the scenario-lane precedent).
        # One shared probe shape: a single warmup run pays the compile
        # and seeds the admission cost estimator, the honesty burst
        # then doubles as the fairness probe's cache warmer; pop 400
        # keeps a run's service time around a second — sub-second runs
        # made the fairness ratio measure OS scheduling jitter,
        # whole-minute runs don't drain inside the probe window.
        from pyabc_tpu.traffic import make_spec

        probe_cls = (TrafficClass("gauss-probe", "gaussian",
                                  weight=1.0, pops=(400,), gens=(3,)),)

        def left() -> float:
            return budget - (clock.now() - t0)

        warm = sched.submit(make_spec(probe_cls[0], seed=seed),
                            tenant_id="probe-warmup")
        warm_by = clock.now() + min(budget * 0.25, 120.0)
        while (warm.state not in ("completed", "failed")
               and clock.now() < warm_by):
            time.sleep(0.25)

        hon_gen = TrafficGenerator(sched, ArrivalSchedule.burst(
            1, burst_size=40, interval_s=1.0, seed=seed + 1,
            classes=probe_cls))
        hon_gen.run(budget_s=max(min(budget * 0.2, 120.0), 20.0))
        hon_rep = hon_gen.report()
        hon_drained = hon_gen.done() and hon_rep["dropped"] == 0
        hon_gen.abort_pending()

        fair_gen = TrafficGenerator(sched, ArrivalSchedule.poisson(
            12, rate_hz=0.5, seed=seed + 2, classes=probe_cls))
        fair_gen.run(budget_s=max(min(budget * 0.15, 90.0), 20.0))
        fair_rep = fair_gen.report()
        fair_drained = (fair_gen.done() and fair_rep["dropped"] == 0
                        and any(n >= 2 for n in
                                fair_rep["completed_by_class"].values()))
        fair_gen.abort_pending()

        # -- phase 1b, the `slo` leg (round 22), part 1: burn-rate
        # fire/clear on the INJECTED clock. The engine's 5m/1h fast
        # windows make a wall-clock assertion impossible inside a
        # minute-scale lane share, so the leg drives the same SloEngine
        # the scheduler runs (counter-ratio SLI, stock thresholds) on a
        # VirtualClock: 100% failures until the page fires, then a
        # drain until the fast pair clears — both latencies RECORDED in
        # injected seconds, the fire/clear itself guarded.
        sclk = VirtualClock()
        sclk.advance(1.0)
        sreg = MetricsRegistry(clock=sclk)
        eng = SloEngine(
            sreg,
            slos=[SLO(name="availability", objective=0.99,
                      good_counter="good_total",
                      bad_counter="bad_total")],
            clock=sclk, sample_interval_s=10.0, register=False)
        sgood = sreg.counter("good_total", "drain successes")
        sbad = sreg.counter("bad_total", "overload failures")
        eng.sample(force=True)
        # warm up ONE HOUR of healthy traffic first: on a cold engine
        # every window falls back to the oldest sample and a single bad
        # batch pages instantly — the latency worth recording is the
        # warmed one, where the 1h window must genuinely roll to 14.4%
        # bad before the page fires
        for _ in range(360):
            sclk.advance(10.0)
            sgood.inc(5)
            eng.sample()
        t_over = sclk.now()
        alert_latency_s = None
        for _ in range(120):
            sclk.advance(10.0)
            sbad.inc(5)
            eng.sample()
            # the FAST page specifically — the slow 6h/3d ticket pair
            # trips earlier under a cold ring (~burn 6 on the total
            # outage) and would otherwise mask the number we're after
            if eng.evaluate("availability")["alerting_fast"]:
                alert_latency_s = sclk.now() - t_over
                break
        t_drain = sclk.now()
        clear_latency_s = None
        for _ in range(800):
            sclk.advance(10.0)
            sgood.inc(5)
            eng.sample()
            if not eng.evaluate("availability")["alerting_fast"]:
                clear_latency_s = sclk.now() - t_drain
                break

        # -- phase 1c, part 2: recorder steady-state overhead. Two
        # warm probe runs (the warmup already paid this shape's
        # compile) through the scheduler — whose per-tenant flight
        # recorder is ALWAYS armed on the run's own tracer/metrics —
        # plus an on-demand snapshot each, then the resilience lane's
        # attributed-wall-clock math over the tenant's private trace:
        # a recorder that stalled the run would read as dark time.
        recorder_fracs = []
        for i in range(2):
            rec_t = sched.submit(make_spec(probe_cls[0], seed=seed + 50 + i),
                                 tenant_id=f"slorec{i}")
            rec_by = clock.now() + min(max(left(), 1.0), 90.0)
            while (rec_t.state not in ("completed", "failed")
                   and clock.now() < rec_by):
                time.sleep(0.1)
            if rec_t.state != "completed":
                break
            fl = rec_t.flight.snapshot(reason="bench")
            sdicts = [sp.to_dict() for sp in rec_t.tracer.spans()]
            cov = coverage_report(
                sdicts, exclude_names=ELASTIC_BLANKET_SPANS)
            recorder_fracs.append({
                "tenant": rec_t.id, "window_s": cov["window_s"],
                "steady_attributed_frac": cov["attributed_frac"],
                "dark_s": cov["dark_s"],
                "flight_entries": len(fl["entries"]),
                "flight_spans": len(fl["spans"]),
            })

        # -- phase 2, CHURN: the seeded open-loop storm at full
        # pressure, with whatever budget the probes left. This phase
        # owns the lifecycle guards (GC, bounded disk, no orphans); its
        # SLO percentiles are RECORDED but not asserted — under
        # sustained open-loop overload the first Retry-After hint
        # legitimately underestimates (new arrivals keep refilling the
        # queue it priced) and completion times reflect backlog depth,
        # not the scheduler's treatment.
        # the `slo` leg part 3 rides the churn: a watcher waits for a
        # mid-flight tenant (>= 1 generation done), takes out a device
        # under it, and verifies the fault path left a parseable flight
        # file whose timeline covers the KILL -> REQUEUE window. The
        # file is read right after the requeue dump lands — before the
        # lifecycle sweep can dispose the tenant and reclaim it.
        chaos_flight = {"armed": False}
        churn_live = {"on": True}

        def _chaos_kill():
            # the churn fleet's smoke tenants finish in well under a
            # second — too fast to catch mid-flight from a poll — so
            # the watcher submits its OWN long-running victim into the
            # storm (retrying through the same 429 backpressure real
            # arrivals see) and takes out a device under it
            victim = None
            by = clock.now() + 90.0
            while churn_live["on"] and clock.now() < by:
                try:
                    victim = sched.submit(
                        TenantSpec(model="gaussian",
                                   population_size=4000,
                                   generations=8, seed=seed + 77,
                                   fused_generations=2),
                        tenant_id="slochaos")
                    break
                except AdmissionRejectedError:
                    time.sleep(2.0)
            if victim is None:
                return
            dev = None
            by = clock.now() + 120.0
            while clock.now() < by:
                lo = victim.submesh_lo
                if lo is not None:
                    dev = lo
                if dev is not None and victim.generations_done >= 1:
                    break
                if victim.state in ("completed", "failed"):
                    break
                time.sleep(0.05)
            if dev is None or victim.state in ("completed", "failed"):
                return
            affected = sched.mark_devices_lost([dev])
            by = clock.now() + 30.0
            while (not os.path.exists(victim.flight_path)
                   and clock.now() < by):
                time.sleep(0.1)
            try:
                payload = read_flight(victim.flight_path)
                kill_ts = next(e["ts"] for e in payload["events"]
                               if e["kind"] == "device_lost")
                req_ts = next(e["ts"] for e in payload["events"]
                              if e["kind"] == "requeued")
                text = render_timeline(payload)
                chaos_flight.update({
                    "armed": True,
                    "ok": bool(
                        victim.id in affected
                        and payload["reason"] == "device_lost"
                        and kill_ts <= req_ts
                        and any(e["kind"] == "device_lost"
                                for e in payload["entries"])
                        and "device_lost" in text),
                    "victim": victim.id,
                    "device": int(dev),
                    "n_affected": len(affected),
                    "kill_to_requeue_s": round(req_ts - kill_ts, 6),
                    "flight_reason": payload["reason"],
                })
            except Exception as e:  # the guard reports, never crashes
                chaos_flight.update(
                    {"armed": True, "ok": False,
                     "error": repr(e)[:200]})

        import threading
        killer = threading.Thread(target=_chaos_kill, daemon=True)
        killer.start()
        gen.run(budget_s=max(left() - 20.0, 30.0))
        rep = gen.report()
        gen.abort_pending()
        churn_live["on"] = False
        killer.join(timeout=60)

        # the scheduler's LIVE SLO state after the storm — the fleet
        # signal the /api/observability block now exports (recorded,
        # not asserted: open-loop overload legitimately burns budget,
        # and the fast windows need minutes of wall clock to roll)
        slo_live = sched.slo.snapshot()

        life = sched.lifecycle.stats()

        # -- bounded disk AFTER all three phases: what is actually on
        # disk vs what the scheduler still tracks (a disposed tenant
        # leaving files behind is exactly the satellite-1 eviction
        # leak)
        live_ids = {st["id"] for st in
                    sched.snapshot()["tenants"]
                    if not st.get("disposed")}
        total_bytes = 0
        orphans = []
        for p in Path(base_dir).rglob("*"):
            if not p.is_file():
                continue
            total_bytes += p.stat().st_size
            rel = p.relative_to(base_dir)
            # ownership = the tenant id prefix of the TOP-level entry
            # (x.db, x.db-wal, x.ck, x.db.columnar/run1/t0.parquet,
            # x.tar.gz all belong to tenant x)
            owner = rel.parts[0].split(".")[0]
            if owner and owner not in live_ids:
                orphans.append(str(rel))

        honesty_armed = hon_drained and hon_rep["honesty_ratio"]["n"] >= 5
        admit_armed = (hon_drained
                       and hon_rep["admission_latency_s"]["n"] >= 10)
        recorder_armed = len(recorder_fracs) == 2
        guard = {
            "pass_slo_fire_and_clear": bool(
                alert_latency_s is not None
                and alert_latency_s <= SLO_ALERT_LATENCY_MAX_S
                and clear_latency_s is not None),
            "pass_flight_on_chaos": (
                bool(chaos_flight.get("ok"))
                if chaos_flight["armed"] else None),
            "pass_recorder_overhead": (
                bool(min(r["steady_attributed_frac"]
                         for r in recorder_fracs)
                     >= SLO_RECORDER_ATTRIBUTED_FRAC_MIN)
                if recorder_armed else None),
            "pass_admission_p99": (
                bool(hon_rep["admission_latency_s"]["p99"]
                     <= TRAFFIC_ADMIT_P99_MAX_S)
                if admit_armed else None),
            "pass_retry_after_honesty": (
                bool(hon_rep["honesty_ratio"]["p90"]
                     <= TRAFFIC_HONESTY_P90_MAX)
                if honesty_armed else None),
            "pass_fairness": (
                bool(fair_rep["fairness_max_ratio"]
                     <= TRAFFIC_FAIRNESS_MAX_RATIO)
                if fair_drained else None),
            "pass_disk_bounded": bool(total_bytes <= disk_budget),
            "pass_no_orphan_files": orphans == [],
            "pass_gc_exercised": bool(
                life["generations_gced_total"] > 0
                or life["tenants_disposed_total"] > 0),
            "sync_budget_strict_armed": bool(
                os.environ.get("PYABC_TPU_SYNC_BUDGET_STRICT") == "1"),
        }
        out.update({
            "metric": "traffic_fleet_churn",
            "lane_s": round(clock.now() - t0, 2),
            "report": rep,
            "slo_leg": {
                "alert_latency_s": alert_latency_s,
                "clear_latency_s": clear_latency_s,
                "alert_latency_max_s": SLO_ALERT_LATENCY_MAX_S,
                "basis": (
                    "injected-clock seconds on the scheduler's own "
                    "SloEngine: 100% failure overload until the fast "
                    "5m/1h pair pages, goods-only drain until it "
                    "clears"),
                "chaos_flight": chaos_flight,
                "recorder_runs": recorder_fracs,
                "recorder_frac_min": SLO_RECORDER_ATTRIBUTED_FRAC_MIN,
                "live": slo_live,
            },
            "honesty_probe": {"drained": hon_drained, **hon_rep},
            "fairness_probe": {"drained": fair_drained, **fair_rep},
            "lifecycle": life,
            "bytes_on_disk_total": int(total_bytes),
            "orphan_files": orphans[:20],
            "admission": sched.snapshot()["admission"],
            "regression_guard": guard,
            "value": 1.0 if all(
                v for v in
                (x for k, x in guard.items() if k.startswith("pass_"))
                if v is not None) else 0.0,
        })
        return out
    finally:
        sched.shutdown()


def run_traffic_lane(budget_s: float) -> dict:
    """Run the traffic lane in a subprocess with 8 forced virtual CPU
    devices and the sync budget STRICT — the same rig as the serve
    lane and the CI ``traffic`` smoke job."""
    budget_s = max(float(budget_s), 45.0)
    env = dict(os.environ)
    env["PYABC_TPU_BENCH_TRAFFIC_CHILD"] = "1"
    env.setdefault("PYABC_TPU_BENCH_TRAFFIC_BUDGET_S",
                   str(budget_s * 0.9))
    env["PYABC_TPU_SYNC_BUDGET_STRICT"] = "1"
    if probe_platform() == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(HERE, "bench.py")],
            env=env, capture_output=True, text=True,
            timeout=budget_s + 60.0,
        )
    except subprocess.TimeoutExpired:
        return {"error": f"traffic lane child timed out after {budget_s}s"}
    for line in reversed(proc.stdout.strip().splitlines() or [""]):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    return {"error": f"traffic lane child rc={proc.returncode}: "
                     f"{(proc.stderr or '')[-400:]}"}


def main():
    from pyabc_tpu.utils.bench_defaults import (
        DEFAULT_BUDGET_S,
        DEFAULT_GENS,
        DEFAULT_POP,
    )

    from pyabc_tpu.observability import SYSTEM_CLOCK, Tracer

    # tests may pre-install a VirtualClock / their own tracer on the
    # module; only fill in what is unset
    global CLOCK, TRACER
    if CLOCK is None:
        CLOCK = SYSTEM_CLOCK
    if TRACER is None:
        TRACER = Tracer(clock=CLOCK)

    budget = float(
        os.environ.get("PYABC_TPU_BENCH_BUDGET_S", DEFAULT_BUDGET_S))
    pop = int(os.environ.get("PYABC_TPU_BENCH_POP", DEFAULT_POP))
    # sizing rationale: pyabc_tpu/utils/bench_defaults.py
    gens = int(os.environ.get("PYABC_TPU_BENCH_GENS", DEFAULT_GENS))
    t_start = CLOCK.now()

    _state["phase"] = "probe"
    platform = probe_platform()
    _state["platform"] = platform
    if platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"

    # `abc-bench --lane mesh`: the MULTICHIP dryrun promoted to a
    # first-class path — run ONLY the mesh lane and emit its JSON line
    if (os.environ.get("PYABC_TPU_BENCH_LANE") or "").strip().lower() \
            == "mesh":
        _state["phase"] = "mesh"
        _state["metric"] = "accepted_particles_per_sec_lv_mesh"
        mesh_skip = mesh_lane_skip_reason()
        if mesh_skip:
            _state["mesh"] = {"skipped": mesh_skip}
        else:
            try:
                _state["mesh"] = run_mesh_lane(
                    budget - max(10.0, 0.05 * budget), platform)
            except Exception as e:
                _state["mesh"] = {"error": repr(e)[:300]}
        _state["value"] = float(
            _state["mesh"].get("accepted_particles_per_sec_mesh") or 0.0)
        _state["partial"] = False
        _state["budget_used_s"] = round(CLOCK.now() - t_start, 1)
        _state["phase"] = "done"
        _emit()
        return

    # `abc-bench --lane scenario`: the scenario-zoo / early-reject lane
    # (ISSUE 15) — Gillespie ON-vs-OFF headline + SIR + K>1 probes
    if (os.environ.get("PYABC_TPU_BENCH_LANE") or "").strip().lower() \
            == "scenario":
        _state["phase"] = "scenario"
        _state["metric"] = "accepted_particles_per_sec_gillespie_early_reject"
        scenario_skip = scenario_lane_skip_reason()
        if scenario_skip:
            _state["scenario"] = {"skipped": scenario_skip}
        else:
            # the inline lane takes ~55% of the budget; the sharded
            # subprocess leg (ISSUE 17: forced-8-device composed
            # sharded+segmented kernel) gets the rest
            inline_budget = (budget - max(10.0, 0.05 * budget)) * 0.55
            try:
                _state["scenario"] = run_scenario_lane(
                    inline_budget, platform)
            except Exception as e:
                _state["scenario"] = {"error": repr(e)[:300]}
            sharded_budget = budget - (CLOCK.now() - t_start) \
                - max(10.0, 0.05 * budget)
            try:
                _state["scenario"]["sharded"] = run_scenario_sharded_leg(
                    sharded_budget, platform)
            except Exception as e:
                _state["scenario"]["sharded"] = {"error": repr(e)[:300]}
        _state["value"] = float(_state["scenario"].get("value") or 0.0)
        _state["util"] = _state["scenario"].get("util", {})
        _state["partial"] = False
        _state["budget_used_s"] = round(CLOCK.now() - t_start, 1)
        _state["phase"] = "done"
        _emit()
        return

    # `abc-bench --lane storage`: ONLY the History-ingest lane (host
    # work — no device, no jax compile; runs inline)
    if (os.environ.get("PYABC_TPU_BENCH_LANE") or "").strip().lower() \
            == "storage":
        _state["phase"] = "storage"
        _state["metric"] = "history_ingest_rows_per_sec_columnar"
        storage_skip = storage_lane_skip_reason()
        if storage_skip:
            _state["storage"] = {"skipped": storage_skip}
        else:
            try:
                _state["storage"] = run_storage_lane(
                    budget - max(5.0, 0.05 * budget))
            except Exception as e:
                _state["storage"] = {"error": repr(e)[:300]}
        _state["value"] = float(_state["storage"].get("value") or 0.0)
        _state["util"] = _state["storage"].get("util", {})
        _state["partial"] = False
        _state["budget_used_s"] = round(CLOCK.now() - t_start, 1)
        _state["phase"] = "done"
        _emit()
        return

    # `abc-bench --lane serve`: ONLY the multi-tenant chaos lane
    if (os.environ.get("PYABC_TPU_BENCH_LANE") or "").strip().lower() \
            == "serve":
        _state["phase"] = "serve"
        _state["metric"] = "serve_mesh_aware_mixed_fleet"
        serve_skip = serve_lane_skip_reason()
        if serve_skip:
            _state["serve"] = {"skipped": serve_skip}
        else:
            try:
                _state["serve"] = run_serve_lane(
                    budget - max(10.0, 0.05 * budget))
            except Exception as e:
                _state["serve"] = {"error": repr(e)[:300]}
        _state["value"] = float(_state["serve"].get("value") or 0.0)
        _state["partial"] = False
        _state["budget_used_s"] = round(CLOCK.now() - t_start, 1)
        _state["phase"] = "done"
        _emit()
        return

    # `abc-bench --lane traffic`: ONLY the fleet-scale churn lane
    # (round 19) — open-loop spec-zoo arrivals + retention/GC guards
    if (os.environ.get("PYABC_TPU_BENCH_LANE") or "").strip().lower() \
            == "traffic":
        _state["phase"] = "traffic"
        _state["metric"] = "traffic_fleet_churn"
        traffic_skip = traffic_lane_skip_reason()
        if traffic_skip:
            _state["traffic"] = {"skipped": traffic_skip}
        else:
            try:
                _state["traffic"] = run_traffic_lane(
                    budget - max(10.0, 0.05 * budget))
            except Exception as e:
                _state["traffic"] = {"error": repr(e)[:300]}
        _state["value"] = float(_state["traffic"].get("value") or 0.0)
        _state["partial"] = False
        _state["budget_used_s"] = round(CLOCK.now() - t_start, 1)
        _state["phase"] = "done"
        _emit()
        return

    # baseline first (cached): it is cheap and makes vs_baseline meaningful
    # even if the main run is cut short
    _state["phase"] = "baseline"
    _state["baseline_kind"] = "self-architecture-proxy"
    baseline_file = os.path.join(HERE, ".baseline_pps")
    if os.path.exists(baseline_file):
        baseline = float(open(baseline_file).read().strip())
    else:
        baseline = run_host_baseline(budget_s=min(120.0, budget / 3))
        with open(baseline_file, "w") as fh:
            fh.write(str(baseline))
    _state["baseline_particles_per_sec"] = round(baseline, 1)

    # spend the budget: repeated fresh runs (new seed each) over the SAME
    # statistical config, OVERLAPPED back-to-back — drain_async lets run
    # k's final fetches hide behind run k+1's compute, so the drain
    # latency that dragged round 4's median is amortized (one exposed
    # drain per BENCH, not per run). Accounting runs on a GLOBAL clock:
    # every chunk completion (from any run/thread) is an event; the
    # steady span (everything after the compile/fill run 0) is split into
    # fixed wall windows and the reported value is the MEDIAN per-window
    # throughput — robust to both tunnel congestion and completion
    # clustering, while summing exactly to the wall time spent.
    _state["phase"] = "bench"
    # persistent XLA compile cache: the G-generation program costs ~15-25s
    # to compile; across driver rounds (and across this loop's fresh runs,
    # should kernel adoption ever fail) it deserializes in ~1s instead
    from pyabc_tpu.utils.xla_cache import setup_xla_cache

    setup_xla_cache(os.path.join(HERE, ".xla_cache"))
    from concurrent.futures import ThreadPoolExecutor

    events: list[dict] = []   # global completion clock, all runs/threads
    run_infos: list[dict] = []
    probe_events: list[tuple[float, float]] = []
    prev_abc = None
    pending_join = None  # (abc, info, seed): drain overlaps the NEXT run
    seed = 0
    errors_in_a_row = 0
    # reserve time for the final drain + emit; spend the rest for real —
    # minus the scale lane's share when it will run (accelerator present
    # or forced)
    reserve = max(12.0, 0.04 * budget)
    scale_skip = scale_lane_skip_reason(platform)
    scale_share = 0.0 if scale_skip else 0.35
    elastic_skip = elastic_lane_skip_reason()
    elastic_share = 0.0 if elastic_skip else 0.12
    resilience_skip = resilience_lane_skip_reason()
    resilience_share = 0.0 if resilience_skip else 0.10
    health_skip = health_lane_skip_reason()
    health_share = 0.0 if health_skip else 0.06
    dispatch_skip = dispatch_lane_skip_reason()
    dispatch_share = 0.0 if dispatch_skip else 0.10
    mesh_skip = mesh_lane_skip_reason()
    mesh_share = 0.0 if mesh_skip else 0.10
    serve_skip = serve_lane_skip_reason()
    serve_share = 0.0 if serve_skip else 0.08
    spend_until = t_start + (budget - reserve) * (
        1.0 - scale_share - elastic_share - resilience_share
        - health_share - dispatch_share - mesh_share - serve_share)
    # per-run host setup (ABCSMC construction, History/sqlite DDL, kernel
    # adoption) runs on this thread OVERLAPPED with the previous run's
    # device chunks — round 5 measured it as dark inter-run wall clock
    setup_pool = ThreadPoolExecutor(
        max_workers=1, thread_name_prefix="pyabc-bench-setup"
    )
    next_prep = None  # (future for (abc, adopted), seed it was built for)
    sync_floor = _sync_floor_s()

    def _finalize_run(abc, info, run_seed):
        try:
            abc.drain_join()
            info["generations_completed"] = int(
                len(abc.history.get_all_populations().query("t >= 0"))
            )
        except Exception as e:
            info["drain_error"] = repr(e)[:300]
        probe_events.extend(abc.probe_events)
        # device-sync accounting: count x measured tunnel floor = the
        # wall clock the latency model attributes to this run's round
        # trips (consumed by the gap_attribution block)
        try:
            info["syncs"] = abc.sync_ledger.summary(sync_floor)
        except Exception as se:
            # a run without sync accounting is reportable, but the gap
            # attribution must say WHY the block is missing (EXC001)
            info["syncs_error"] = repr(se)[:200]
        run_infos.append({"seed": run_seed, **info})

    while True:
        remaining = spend_until - CLOCK.now()
        if seed > 0 and remaining < 10.0:
            break

        def on_event(ev, _r=seed):
            ev["run"] = _r
            events.append(ev)

        prebuilt = None
        if next_prep is not None and next_prep[1] == seed:
            try:
                prebuilt = next_prep[0].result()
            except Exception:
                prebuilt = None  # build inline below
        next_prep = None
        try:
            # seed 0 gets a compile-proof floor; later runs must respect
            # the remaining budget exactly, or the last run overshoots
            # into the driver's SIGTERM and the final drain/emit is lost
            if prebuilt is None:
                prebuilt = build_bench_run(pop, seed, prev_abc)
            # pre-build run seed+1's host objects NOW: the setup thread
            # works while THIS run's chunks occupy the device/tunnel
            next_prep = (
                setup_pool.submit(build_bench_run, pop, seed + 1,
                                  prebuilt[0]),
                seed + 1,
            )
            abc, info = run_tpu_bench(
                pop_size=pop, n_gens=gens,
                budget_s=(max(remaining, 60.0) if seed == 0
                          else remaining), seed=seed,
                prev_abc=prev_abc, on_event=on_event, prebuilt=prebuilt,
            )
        except Exception as e:  # keep earlier runs' results on a crash
            run_infos.append({"seed": seed, "error": repr(e)[:300]})
            errors_in_a_row += 1
            if errors_in_a_row >= 2 or seed == 0:
                break  # persistent failure (or no kernels to salvage)
            # one-off failure (tunnel hiccup): settle the previous run's
            # drain, drop kernel adoption AND the prebuilt run (it may
            # have adopted the failed run's context), try fresh
            if pending_join is not None:
                _finalize_run(*pending_join)
                pending_join = None
            prev_abc = None
            next_prep = None
            seed += 1
            # keep the emit-on-signal JSON current through the retry
            _update_headline(events, run_infos, baseline,
                             spans=TRACER.spans())
            continue
        errors_in_a_row = 0
        # join the PREVIOUS run's drain now — its fetches overlapped this
        # run's compute, so the join is (nearly) free
        if pending_join is not None:
            _finalize_run(*pending_join)
        pending_join = (abc, info, seed)
        prev_abc = abc
        seed += 1
        # keep headline fields current so a SIGTERM still emits real data
        _update_headline(events, run_infos, baseline,
                         spans=TRACER.spans())
    if pending_join is not None:
        # the final run's drain is the bench's ONE exposed drain
        _finalize_run(*pending_join)
    setup_pool.shutdown(wait=False, cancel_futures=True)

    # -- scale lane: the pop-16k LocalTransition measurement (or its
    # recorded skip reason — never silent)
    if scale_skip:
        _state["scale"] = {"skipped": scale_skip}
    else:
        _state["phase"] = "scale"
        try:
            _state["scale"] = run_scale_lane(
                t_start + budget - reserve - CLOCK.now()
                - (budget - reserve) * (elastic_share + resilience_share
                                        + health_share
                                        + dispatch_share))
        except Exception as e:
            _state["scale"] = {"error": repr(e)[:300]}

    # -- elastic lane: worker-tracing attribution on the broker path
    # (round 8; CPU-capable — or its recorded skip reason, never silent)
    if elastic_skip:
        _state["elastic"] = {"skipped": elastic_skip}
    else:
        _state["phase"] = "elastic"
        try:
            _state["elastic"] = run_elastic_lane(
                max(t_start + budget - reserve - CLOCK.now()
                    - (budget - reserve)
                    * (resilience_share + health_share
                       + dispatch_share), 20.0))
        except Exception as e:
            _state["elastic"] = {"error": repr(e)[:300]}

    # -- resilience lane: self-healing under injected worker kills
    # (round 9; CPU-capable — or its recorded skip reason, never silent)
    if resilience_skip:
        _state["resilience"] = {"skipped": resilience_skip}
    else:
        _state["phase"] = "resilience"
        try:
            _state["resilience"] = run_resilience_lane(
                max(t_start + budget - reserve - CLOCK.now()
                    - (budget - reserve)
                    * (health_share + dispatch_share), 20.0))
        except Exception as e:
            _state["resilience"] = {"error": repr(e)[:300]}

    # -- health lane: in-kernel health guards + rollback recovery
    # (round 10; CPU-capable — or its recorded skip reason, never silent)
    if health_skip:
        _state["health"] = {"skipped": health_skip}
    else:
        _state["phase"] = "health"
        try:
            _state["health"] = run_health_lane(
                max(t_start + budget - reserve - CLOCK.now()
                    - (budget - reserve) * dispatch_share, 15.0))
        except Exception as e:
            _state["health"] = {"error": repr(e)[:300]}

    # -- dispatch lane: the single async dispatch engine's dual-basis
    # ratio + sync budget (round 12; CPU-capable — or its recorded skip
    # reason, never silent)
    if dispatch_skip:
        _state["dispatch"] = {"skipped": dispatch_skip}
    else:
        _state["phase"] = "dispatch"
        try:
            _state["dispatch"] = run_dispatch_lane(
                max(t_start + budget - reserve - CLOCK.now()
                    - (budget - reserve) * mesh_share, 25.0))
        except Exception as e:
            _state["dispatch"] = {"error": repr(e)[:300]}

    # -- mesh lane: sharded fused sampling on the device mesh (round 13;
    # runs in a forced-8-device subprocess — or its recorded skip
    # reason, never silent)
    if mesh_skip:
        _state["mesh"] = {"skipped": mesh_skip}
    else:
        _state["phase"] = "mesh"
        try:
            _state["mesh"] = run_mesh_lane(
                max(t_start + budget - reserve - CLOCK.now()
                    - (budget - reserve) * serve_share, 60.0),
                platform)
        except Exception as e:
            _state["mesh"] = {"error": repr(e)[:300]}

    # -- serve lane: multi-tenant chaos containment (round 14;
    # CPU-capable — or its recorded skip reason, never silent)
    if serve_skip:
        _state["serve"] = {"skipped": serve_skip}
    else:
        _state["phase"] = "serve"
        try:
            _state["serve"] = run_serve_lane(
                max(t_start + budget - reserve - CLOCK.now(), 45.0))
        except Exception as e:
            _state["serve"] = {"error": repr(e)[:300]}

    _state["budget_used_s"] = round(CLOCK.now() - t_start, 1)
    _state["pop_size"] = pop
    _update_headline(events, run_infos, baseline, probe_events,
                     spans=TRACER.spans())
    _state["phase"] = "done"
    _emit()


def _window_s() -> float:
    """Wall-window width for the strict global-clock median. Resolved
    lazily: importing pyabc_tpu at bench module load would touch JAX
    before main() decides the platform."""
    from pyabc_tpu.utils.bench_defaults import DEFAULT_WINDOW_S

    return float(
        os.environ.get("PYABC_TPU_BENCH_WINDOW_S") or DEFAULT_WINDOW_S
    )


def _sync_floor_s() -> float:
    """The per-round-trip latency floor the sync-accounting model
    multiplies by (~102 ms measured on the axon tunnel, round 5;
    override with PYABC_TPU_SYNC_FLOOR_S on a co-located host)."""
    from pyabc_tpu.observability import DEFAULT_SYNC_FLOOR_S

    return float(
        os.environ.get("PYABC_TPU_SYNC_FLOOR_S") or DEFAULT_SYNC_FLOOR_S
    )


def _update_headline(events, run_infos, baseline, probe_events=None,
                     spans=None) -> None:
    """Refresh the emit-on-signal headline fields from the global
    completion-event clock — shared by the loop body and the final
    report so the SIGTERM-path JSON can never desynchronize from it.

    Basis: events of run 0 (compile + pipeline fill) are warmup. The
    steady span runs from the last warmup completion preceding the first
    run>=1 event (so the first steady window has a defined start) to the
    newest completion. The span is cut into WINDOW_S wall windows;
    pps per window = accepted particles completing in it / WINDOW_S.
    Every second of the span lands in exactly one window — overlapped
    drains, congestion stalls and completion clustering all average into
    the windows they actually occupied."""
    import statistics

    evs = sorted(events, key=lambda e: e["ts"])
    steady = [e for e in evs if e.get("run", 0) >= 1]
    # bounded run detail for the JSON line
    _state["runs"] = (
        run_infos if len(run_infos) <= 6
        else run_infos[:5] + [{"elided_runs": len(run_infos) - 5}]
    )
    _state["n_chunk_events"] = len(evs)
    if not steady:
        if evs:
            # only the warmup run completed: includes-compile estimate
            span = evs[-1]["ts"] - (evs[0]["ts"] - evs[0]["chunk_s"])
            n_acc = sum(e["n_acc"] for e in evs)
            _state["value"] = round(n_acc / max(span, 1e-9), 1)
            _state["vs_baseline"] = round(_state["value"] / baseline, 2)
            _state["steady_state_basis"] = (
                "single warmup run (includes compile)"
            )
        return
    # -- headline: median over warm runs of the PIPELINE-FULL span
    # throughput — particles completing after each run's fill chunk,
    # divided by the wall from the fill chunk's completion to the run's
    # last completion. This is the round-4 "steady chunk" concept made
    # span-based: per-run warmup (calibration, gen 0, pipeline fill) is
    # excluded exactly as in rounds 1-4, but a span cannot be gamed by
    # completion clustering the way a per-chunk fetch clock can once
    # drains overlap (a per-chunk median over clustered drain completions
    # read 280k+ where the span says what was actually sustained). The
    # STRICTER all-inclusive number is wall_clock below; quote both.
    run_pps = []
    by_run: dict[int, list] = {}
    for e in steady:
        by_run.setdefault(e["run"], []).append(e)
    for evr in by_run.values():
        fill = next((e for e in evr if e["chunk_index"] == 1), None)
        rest = [e for e in evr if e["chunk_index"] >= 2]
        if fill is None or not rest:
            continue
        span = max(e["ts"] for e in rest) - fill["ts"]
        if span > 0:
            run_pps.append(sum(e["n_acc"] for e in rest) / span)
    if run_pps:
        _state["value"] = round(statistics.median(run_pps), 1)
        _state["vs_baseline"] = round(_state["value"] / baseline, 2)
        _state["partial"] = False
        _state["n_steady_runs"] = len(run_pps)
        _state["steady_pps_best"] = round(max(run_pps), 1)
        _state["steady_pps_worst"] = round(min(run_pps), 1)
        _state["steady_state_basis"] = (
            f"median over {len(run_pps)} warm runs of pipeline-full span "
            f"throughput (post-fill chunks / span from fill completion "
            f"to last completion; per-run calibration+gen0+fill excluded "
            f"as in rounds 1-4; drains overlap the next run)"
        )
    # -- strictest accounting (new in round 5): the global completion
    # clock over the whole steady span, cut into fixed wall windows —
    # includes per-run setup, calibration, gen 0, pipeline fill and
    # drains; every second of wall is in exactly one window. This is the
    # end-to-end number a user running back-to-back studies observes.
    i0 = evs.index(steady[0])
    t0 = evs[i0 - 1]["ts"] if i0 > 0 else steady[0]["ts"] - \
        steady[0]["chunk_s"]
    t_end = evs[-1]["ts"]
    win = _window_s()
    # the window math lives in the observability subsystem now
    # (coverage.window_throughput, unit-tested); semantics identical to
    # the round-5 hand-rolled version: EVERY completion inside the span
    # counts, including run 0's drain chunks finishing behind run 1's
    # compute — their wall time is in the denominator, so dropping their
    # particles would bias the strict metric low (run 0 only defines
    # where the span STARTS)
    from pyabc_tpu.observability import (
        coverage_report,
        device_busy_spans,
        interval_intersection,
        interval_union,
        window_throughput,
    )

    wt = window_throughput(
        ((e["ts"], e["n_acc"]) for e in evs), t0, t_end, win
    )
    span = wt["span_s"]
    in_span = [e for e in evs if t0 < e["ts"] <= t0 + span]
    _state["wall_clock"] = {
        "median_window_pps": round(statistics.median(wt["per_window"]), 1),
        "aggregate_pps": round(wt["aggregate_per_s"], 1),
        "n_windows": wt["n_windows"],
        "window_s": win,
        "basis": (
            "global completion clock over the full steady span "
            "(includes per-run setup, calibration, gen 0, fill, drains)"
        ),
    }
    # -- observability: the coverage accountant's attributed-wall-clock
    # numbers over the SAME steady window as wall_clock, plus one
    # attributed fraction per warm run — the round-5 "~60% dark time"
    # verdict as a reported, trackable quantity (BENCH observability
    # block, consumed by future rounds)
    if spans is not None:
        # exclude the per-run root "run" spans: they blanket their run's
        # whole window, and the question is how much the WORK spans
        # (chunk/fetch/process/dispatch/db.write/...) explain
        sdicts = [s.to_dict() for s in spans]
        steady_cov = coverage_report(sdicts, t0, t0 + span,
                                     exclude_names=("run",))
        per_run = []
        for r in sorted(by_run):
            evr = by_run[r]
            r0 = min(e["ts"] - e.get("chunk_s", 0.0) for e in evr)
            r1 = max(e["ts"] for e in evr)
            cov = coverage_report(sdicts, r0, r1, exclude_names=("run",))
            per_run.append({
                "run": r,
                "attributed_frac": cov["attributed_frac"],
                "window_s": cov["window_s"],
            })
        _state["observability"] = {
            "n_spans": len(sdicts),
            "steady_attributed_frac": steady_cov["attributed_frac"],
            "steady_dark_s": steady_cov["dark_s"],
            "per_warm_run": per_run,
            "basis": (
                "fraction of the window covered by >=1 tracer span "
                "(any thread); dark_s is wall clock no span explains"
            ),
        }
        if probe_events:
            # device-busy pseudo-thread (ROADMAP "device-busy
            # correlation"): consecutive compute-probe completions become
            # measured device.busy spans on a synthetic thread, fed to
            # the SAME coverage accountant — the host-only attribution
            # fields above keep their round-6 semantics, these ADD the
            # device side and split chunk-fetch waits into "device still
            # computing" vs "host waiting on the tunnel"
            dev_spans = device_busy_spans(probe_events)
            cov_dev = coverage_report(sdicts + dev_spans, t0, t0 + span,
                                      exclude_names=("run",))
            dev_thread = cov_dev["per_thread"].get("device", {})
            fetch_ivs = [
                (d["start"], d["end"]) for d in sdicts
                if d["name"] == "fetch" and d["end"] is not None
                and t0 < d["end"] and d["start"] < t0 + span
            ]
            busy_ivs = [(d["start"], d["end"]) for d in dev_spans]
            fetch_total = interval_union(fetch_ivs)
            fetch_busy = interval_intersection(fetch_ivs, busy_ivs)
            _state["observability"].update({
                "device_busy_frac": dev_thread.get("attributed_frac", 0.0),
                "steady_attributed_frac_with_device":
                    cov_dev["attributed_frac"],
                "fetch_wait_s": round(fetch_total, 6),
                "fetch_wait_device_computing_s": round(fetch_busy, 6),
                "fetch_wait_tunnel_exposed_s": round(
                    fetch_total - fetch_busy, 6),
                "device_basis": (
                    "device.busy pseudo-spans derived from consecutive "
                    "compute_probe completions (upper bound: each probe "
                    "pays one pipelined tunnel round trip); fetch-wait "
                    "split = fetch spans intersected with device.busy"
                ),
            })
    # activity breakdown over the steady span (VERDICT r4 #8). The
    # numerators are per-THREAD blocking seconds: concurrent fetch waits
    # overlap each other and the device's compute (that overlap is the
    # round-5 design), so these are in-flight ratios, NOT exclusive wall
    # shares, and need not sum to 1.
    _state["util"] = {
        "fetch_in_flight_frac": round(
            sum(e["fetch_s"] for e in in_span) / span, 4),
        "host_process_frac": round(
            sum(e["process_s"] for e in in_span) / span, 4),
        "dispatch_frac": round(
            sum(e["dispatch_s"] for e in in_span) / span, 4),
    }
    # fetch-payload telemetry (round-6 compaction, regression-guarded):
    # measured post-compaction wire bytes per chunk vs what the round-5
    # full-f32-ring fetch would have moved for the SAME chunks
    fb = [e["fetch_bytes"] for e in in_span if e.get("fetch_bytes")]
    fb_full = [e["fetch_bytes_full_f32"] for e in in_span
               if e.get("fetch_bytes_full_f32")]
    if fb:
        _state["util"]["fetch_bytes_per_chunk"] = int(
            statistics.median(fb))
        if fb_full:
            _state["util"]["fetch_bytes_per_chunk_r5_equiv"] = int(
                statistics.median(fb_full))
            _state["util"]["fetch_payload_reduction_x"] = round(
                statistics.median(fb_full) / max(statistics.median(fb), 1),
                2,
            )
    # device-sync accounting per warm run (round-6: residual-gap
    # ATTRIBUTION instead of assumption)
    sync_floor = _sync_floor_s()
    warm_syncs = [r["syncs"]["syncs"] for r in run_infos
                  if r.get("seed", 0) >= 1 and "syncs" in r]
    if warm_syncs:
        _state["util"]["syncs_per_run"] = int(
            statistics.median(warm_syncs))
        _state["util"]["sync_floor_s"] = sync_floor
        _state["util"]["tunnel_floor_s_per_run"] = round(
            statistics.median(warm_syncs) * sync_floor, 3)
    # residual-gap attribution (round 6, VERDICT r5 Next #1c): how much
    # of the steady span's DARK wall clock (no tracer span explains it)
    # does the sync-latency model — recorded device round trips x the
    # measured tunnel floor — account for? A fraction >= 0.9 means the
    # remaining wall-clock gap is the tunnel's latency floor, an
    # environment property, not unattributed host work.
    dark_s = _state.get("observability", {}).get("steady_dark_s")
    if warm_syncs and dark_s is not None:
        floor_total = sum(warm_syncs) * sync_floor
        _state["gap_attribution"] = {
            "sync_floor_s": sync_floor,
            "warm_run_syncs_total": int(sum(warm_syncs)),
            "tunnel_floor_s_total": round(floor_total, 3),
            "steady_dark_s": dark_s,
            "dark_explained_by_sync_floor_frac": (
                round(min(1.0, floor_total / dark_s), 4)
                if dark_s > 0 else 1.0
            ),
            "basis": (
                "recorded device syncs (chunk fetches, compute probes, "
                "collects) x the measured per-round-trip tunnel floor, "
                "vs steady-span wall clock outside every tracer span; "
                "an upper-bound latency model — each sync can expose at "
                "most the floor outside spans"
            ),
        }
    if probe_events:
        probes = sorted(p for p in probe_events
                        if t0 <= p[1] <= t0 + span)
        busy = 0.0
        prev_done = None
        for disp, done in probes:
            start = disp if prev_done is None else max(prev_done, disp)
            busy += max(done - start, 0.0)
            prev_done = done
        _state["util"]["device_busy_frac_upper"] = round(busy / span, 4)
        _state["util"]["basis"] = (
            "numerators are per-thread blocking seconds over the steady "
            "span (concurrent waits overlap; fracs need not sum to 1); "
            "device busy is an UPPER bound from per-chunk completion "
            "probes (each probe pays the ~0.1s tunnel sync floor, so "
            "short chunks read as floor-length)"
        )


if __name__ == "__main__":
    if os.environ.get("PYABC_TPU_BENCH_MESH_CHILD"):
        # mesh-lane subprocess: the multi-device platform is already
        # configured in the environment; disarm the emit-once hook (its
        # headline JSON would shadow the lane's line at exit) and print
        # ONE JSON line
        _emitted = True
        print(json.dumps(_mesh_lane_child()))
        sys.exit(0)
    if os.environ.get("PYABC_TPU_BENCH_MESH_MH_ROLE"):
        # multihost-leg subprocess: roles "0"/"1" join the 2-process gloo
        # runtime over the localhost coordinator; role "ref" is the
        # 1-process virtual-shard reference the digests are checked against
        _emitted = True
        print(json.dumps(
            _mesh_multihost_worker(os.environ["PYABC_TPU_BENCH_MESH_MH_ROLE"])))
        sys.exit(0)
    if os.environ.get("PYABC_TPU_BENCH_SCENARIO_SHARDED_CHILD"):
        # sharded scenario leg subprocess: same contract as the mesh
        # child (forced 8 devices + strict sync budget in the env)
        _emitted = True
        print(json.dumps(_scenario_sharded_child()))
        sys.exit(0)
    if os.environ.get("PYABC_TPU_BENCH_SERVE_CHILD"):
        # serve-lane subprocess: same contract as the mesh child
        _emitted = True
        print(json.dumps(_serve_lane_child()))
        sys.exit(0)
    if os.environ.get("PYABC_TPU_BENCH_TRAFFIC_CHILD"):
        # traffic-lane subprocess: same contract as the serve child
        _emitted = True
        print(json.dumps(_traffic_lane_child()))
        sys.exit(0)
    main()
