"""Benchmark harness — prints ONE JSON line for the driver, ALWAYS.

Metric (BASELINE.md): accepted particles/sec per SMC generation on the
Lotka-Volterra ODE config (4 params, AdaptivePNormDistance, MedianEpsilon).

Robustness contract (a bench that can die silently is not a bench):
- A walltime budget (``PYABC_TPU_BENCH_BUDGET_S``, default 300s) caps the
  run via ABCSMC's ``max_walltime`` stopping rule.
- The JSON line is emitted even on partial completion, SIGTERM/SIGINT
  (driver timeout sends SIGTERM before SIGKILL), or an exception — via a
  process-level emit-once hook fed with whatever was measured so far.
- The TPU runtime is probed in a SUBPROCESS with its own timeout first; a
  broken/hung tunnel (this round-1 failure mode) downgrades to the CPU
  platform instead of eating the whole budget.
- ``baseline_kind`` labels the baseline honestly: it is this repo's own
  scalar host path x assumed cores (``self-architecture-proxy``), because
  the reference mount is empty and there is no network (BASELINE.md).

Env knobs: PYABC_TPU_BENCH_POP (default 1000), PYABC_TPU_BENCH_GENS (31),
PYABC_TPU_BENCH_G (fused generations per chunk, 16),
PYABC_TPU_BENCH_BUDGET_S (300), PYABC_TPU_BENCH_CPU=1 (force CPU platform),
PYABC_TPU_BENCH_STORE_SS=1 (store per-particle sum stats in the db).
"""
import atexit
import json
import os
import signal
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))

# -- emit-once machinery ------------------------------------------------------

_state = {
    "metric": "accepted_particles_per_sec_lotka_volterra",
    "value": 0.0,
    "unit": "particles/s",
    "vs_baseline": 0.0,
    "partial": True,
    "phase": "startup",
}
_emitted = False


def _emit():
    global _emitted
    if _emitted:
        return
    _emitted = True
    print(json.dumps(_state), flush=True)


def _on_signal(signum, frame):
    _state["phase"] = f"killed_by_signal_{signum}"
    _emit()
    os._exit(1)


atexit.register(_emit)
signal.signal(signal.SIGTERM, _on_signal)
signal.signal(signal.SIGINT, _on_signal)


# -- platform probe -----------------------------------------------------------

def probe_platform(timeout_s: float = 90.0) -> str:
    """Probe the default JAX backend in a subprocess; never hang the bench.

    Returns the platform name to use ('tpu'/'axon'/'cpu'). A hung or broken
    accelerator runtime (round-1: libtpu mismatch under the axon tunnel ate
    the entire bench budget) downgrades to 'cpu'.
    """
    if os.environ.get("PYABC_TPU_BENCH_CPU"):
        return "cpu"
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices()[0]; "
             "import jax.numpy as jnp; jnp.zeros(8).block_until_ready(); "
             "print(d.platform)"],
            capture_output=True, text=True, timeout=timeout_s,
        )
        if proc.returncode == 0:
            return proc.stdout.strip().splitlines()[-1]
    except subprocess.TimeoutExpired:
        pass
    return "cpu"


# -- benchmark runs -----------------------------------------------------------

def run_tpu_bench(pop_size: int, n_gens: int, budget_s: float, seed: int = 0,
                  prev_abc=None):
    import pandas as pd

    import pyabc_tpu as pt
    from pyabc_tpu.models import lotka_volterra as lv
    from pyabc_tpu.utils.bench_defaults import DEFAULT_G

    model = lv.make_lv_model()
    prior = lv.default_prior()
    obs = lv.observed_data(seed=123)

    abc = pt.ABCSMC(
        model, prior,
        pt.AdaptivePNormDistance(p=2),
        population_size=pop_size,
        eps=pt.MedianEpsilon(),
        seed=seed,
        fused_generations=int(os.environ.get("PYABC_TPU_BENCH_G", DEFAULT_G)),
    )
    # skip per-particle sumstat storage (and with it the dominant share of
    # the per-chunk device->host fetch) unless explicitly requested
    store_ss = bool(os.environ.get("PYABC_TPU_BENCH_STORE_SS"))
    abc.new("sqlite://", obs, store_sum_stats=store_ss)
    adopted = False
    if prev_abc is not None:
        # identical statistical config across seeds: reuse the previous
        # run's compiled kernels so later runs are pure steady state
        try:
            abc.adopt_device_context(prev_abc)
            adopted = True
        except Exception:
            pass
    t0 = time.time()
    h = abc.run(max_nr_populations=n_gens + 2, max_walltime=budget_s)
    total = time.time() - t0

    pops = h.get_all_populations()
    pops = pops[pops.t >= 0]
    ends = pd.to_datetime(pops["population_end_time"])
    info = dict(total_s=round(total, 2), pop_size=pop_size,
                generations_completed=int(len(pops)),
                total_sims=int(h.total_nr_simulations),
                adopted_kernels=adopted)

    # fused multi-generation path: per-chunk fetch-to-fetch periods are the
    # honest steady-state clock (populations of one chunk persist in a
    # burst, so end-time spacing is meaningless). Chunk 1 of a fresh run
    # carries the one-off XLA compile of the G-generation program; a run
    # that adopted the previous run's kernels has no compile chunk at all.
    # count PERSISTED generations per chunk (a chunk that stopped early has
    # fewer telemetry rows than its planned fused_chunk size)
    chunks: dict[int, tuple[int, float]] = {}
    for t in range(h.max_t + 1):
        tel = h.get_telemetry(t)
        ci = tel.get("chunk_index")
        if ci:
            g_done = chunks.get(ci, (0, 0.0))[0] + 1
            chunks[ci] = (g_done, float(tel["chunk_s"]))
    if chunks:
        info["fused_chunks"] = [
            {"gens": g, "period_s": round(s, 3)}
            for _, (g, s) in sorted(chunks.items())
        ]
        # chunk 1 is never steady state: for a fresh run it carries the XLA
        # compile; for an adopted run it still absorbs pipeline fill (the
        # dispatch ramp after generation 0's single-generation kernel).
        # Tail chunks with fewer than G generations amortize the per-chunk
        # sync over a stub and are schedule artifacts, not throughput
        # windows — excluded as well.
        first_ci = min(chunks)
        g_full = max(g for g, _ in chunks.values())
        steady = {
            ci: (g, s) for ci, (g, s) in chunks.items()
            if ci >= first_ci + 1 and g == g_full
        }
        if not adopted:
            info["compile_chunk_s"] = round(chunks[first_ci][1], 2)
        steady_pps = [
            pop_size * g / max(s, 1e-9) for g, s in steady.values()
        ]
        if not steady_pps:
            # only the compile chunk completed: offer an includes-compile
            # estimate for the partial-result path
            gens = sum(g for g, _ in chunks.values())
            secs = sum(s for _, s in chunks.values())
            info["fallback_pps_includes_compile"] = round(
                pop_size * gens / max(secs, 1e-9), 1
            )
        return steady_pps, info, abc

    # per-generation path: end-time spacing, excluding the two compile gens
    gen_durs = [
        round((ends.iloc[i + 1] - ends.iloc[i]).total_seconds(), 2)
        for i in range(len(ends) - 1)
    ]
    info["gen_durations_s"] = gen_durs
    if len(ends) >= 1:
        info["setup_and_gen0_s"] = round(
            total - (ends.iloc[-1] - ends.iloc[0]).total_seconds(), 2
        )
    if len(ends) >= 3:
        gens = len(ends) - 2
        elapsed = (ends.iloc[-1] - ends.iloc[1]).total_seconds()
        return [pop_size * gens / max(elapsed, 1e-9)], info, abc
    if len(ends) >= 1:
        # partial run: count everything (includes compile — labeled partial)
        info["note"] = "includes compile (no steady window completed)"
        return [pop_size * len(ends) / max(total, 1e-9)], info, abc
    info["note"] = "no generation completed within budget"
    return [], info, abc


def run_host_baseline(pop_size: int = 60, n_gens: int = 2, seed: int = 0,
                      assumed_cores: int = 8, budget_s: float = 120.0):
    """Reference-ARCHITECTURE throughput proxy on this machine: the scalar
    host closure path (reference-faithful simulate_one loop) via
    SingleCoreSampler, scaled by assumed_cores as an upper bound on
    MulticoreEvalParallelSampler. Replace with a real pyABC run the moment
    the reference mount/network appears (BASELINE.md).

    Runs in a SUBPROCESS with JAX_PLATFORMS=cpu: the reference is a CPU
    framework, and the scalar path issues one tiny dispatch per simulation —
    over the axon TPU tunnel each dispatch pays ~0.1s of RPC latency, which
    would understate the baseline ~25x and flatter vs_baseline dishonestly.
    """
    code = f"""
import time, numpy as np
import jax
# the axon plugin ignores JAX_PLATFORMS; pin the default device instead
jax.config.update("jax_default_device", jax.devices("cpu")[0])
import pyabc_tpu as pt
from pyabc_tpu.models import lotka_volterra as lv
model = lv.make_lv_model(); prior = lv.default_prior()
obs = lv.observed_data(seed=123)
np.random.seed({seed})
abc = pt.ABCSMC(model, prior, pt.PNormDistance(p=2),
                population_size={pop_size},
                eps=pt.QuantileEpsilon(initial_epsilon=200.0, alpha=0.5),
                sampler=pt.SingleCoreSampler())
abc.new("sqlite://", obs)
t0 = time.time()
h = abc.run(max_nr_populations={n_gens}, max_walltime={budget_s})
elapsed = time.time() - t0
print("BASELINE_PPS", {pop_size} * h.n_populations / elapsed * {assumed_cores})
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=budget_s + 120, env=env, cwd=HERE,
    )
    for line in proc.stdout.splitlines():
        if line.startswith("BASELINE_PPS"):
            return float(line.split()[1])
    raise RuntimeError(
        f"host baseline failed: rc={proc.returncode}\n{proc.stderr[-2000:]}"
    )


def main():
    from pyabc_tpu.utils.bench_defaults import (
        DEFAULT_BUDGET_S,
        DEFAULT_GENS,
        DEFAULT_POP,
    )

    budget = float(
        os.environ.get("PYABC_TPU_BENCH_BUDGET_S", DEFAULT_BUDGET_S))
    pop = int(os.environ.get("PYABC_TPU_BENCH_POP", DEFAULT_POP))
    # sizing rationale: pyabc_tpu/utils/bench_defaults.py
    gens = int(os.environ.get("PYABC_TPU_BENCH_GENS", DEFAULT_GENS))
    t_start = time.time()

    _state["phase"] = "probe"
    platform = probe_platform()
    _state["platform"] = platform
    if platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"

    # baseline first (cached): it is cheap and makes vs_baseline meaningful
    # even if the main run is cut short
    _state["phase"] = "baseline"
    _state["baseline_kind"] = "self-architecture-proxy"
    baseline_file = os.path.join(HERE, ".baseline_pps")
    if os.path.exists(baseline_file):
        baseline = float(open(baseline_file).read().strip())
    else:
        baseline = run_host_baseline(budget_s=min(120.0, budget / 3))
        with open(baseline_file, "w") as fh:
            fh.write(str(baseline))
    _state["baseline_particles_per_sec"] = round(baseline, 1)

    # spend the budget: repeated fresh runs (new seed each) over the SAME
    # statistical config; run 2+ adopts run 1's compiled kernels, so every
    # one of its chunks is a steady-state window. The reported value is the
    # MEDIAN per-chunk throughput over all steady windows — one congested
    # tunnel sample (BASELINE.md: variance up to 2x) can't set the record.
    _state["phase"] = "bench"
    # persistent XLA compile cache: the G-generation program costs ~15-25s
    # to compile; across driver rounds (and across this loop's fresh runs,
    # should kernel adoption ever fail) it deserializes in ~1s instead
    try:
        import jax

        cache_dir = os.environ.get(
            "JAX_COMPILATION_CACHE_DIR", os.path.join(HERE, ".xla_cache")
        )
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass
    steady_all: list[float] = []
    run_infos: list[dict] = []
    fallbacks: list[float] = []
    prev_abc = None
    seed = 0
    # reserve time for the final emit + a safety margin against overshoot
    spend_until = t_start + 0.85 * budget
    while True:
        remaining = min(budget - (time.time() - t_start) - 10.0,
                        spend_until - time.time())
        if seed > 0 and (remaining < 15.0 or len(steady_all) >= 120):
            break
        try:
            pps_list, info, abc = run_tpu_bench(
                pop_size=pop, n_gens=gens,
                budget_s=max(remaining, 30.0), seed=seed, prev_abc=prev_abc,
            )
        except Exception as e:  # keep earlier runs' results on a late crash
            run_infos.append({"seed": seed, "error": repr(e)[:300]})
            break
        steady_all.extend(pps_list)
        if "fallback_pps_includes_compile" in info:
            fallbacks.append(info["fallback_pps_includes_compile"])
        run_infos.append({
            "seed": seed,
            "steady_chunk_pps": [round(p, 1) for p in pps_list],
            **{k: info[k] for k in ("total_s", "generations_completed",
                                    "compile_chunk_s", "adopted_kernels",
                                    "fused_chunks", "note")
               if k in info},
        })
        prev_abc = abc
        seed += 1
        # keep headline fields current so a SIGTERM still emits real data
        _update_headline(steady_all, run_infos, pop, baseline)

    _state["budget_used_s"] = round(time.time() - t_start, 1)
    _update_headline(steady_all, run_infos, pop, baseline)
    if steady_all:
        _state["steady_pps_best"] = round(max(steady_all), 1)
        _state["steady_pps_worst"] = round(min(steady_all), 1)
        _state["steady_state_basis"] = (
            f"median over {len(steady_all)} steady chunks across "
            f"{len([r for r in run_infos if 'error' not in r])} runs"
        )
    elif fallbacks:
        _state["value"] = round(max(fallbacks), 1)
        _state["vs_baseline"] = round(_state["value"] / baseline, 2)
        _state["steady_state_basis"] = "single chunk (includes compile)"
    _state["phase"] = "done"
    _emit()


def _update_headline(steady_all, run_infos, pop, baseline) -> None:
    """Refresh the emit-on-signal headline fields (median over steady
    chunks, bounded run detail) — shared by the loop body and the final
    report so the SIGTERM-path JSON can never desynchronize from it."""
    import statistics

    if steady_all:
        _state["value"] = round(statistics.median(steady_all), 1)
        _state["vs_baseline"] = round(_state["value"] / baseline, 2)
        _state["partial"] = False
    # keep the JSON line bounded: full detail for the first runs only
    _state["runs"] = (
        run_infos if len(run_infos) <= 6
        else run_infos[:5] + [{"elided_runs": len(run_infos) - 5}]
    )
    _state["pop_size"] = pop
    _state["n_steady_chunks"] = len(steady_all)


if __name__ == "__main__":
    main()
