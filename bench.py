"""Benchmark harness — prints ONE JSON line for the driver.

Metric (BASELINE.md): accepted particles/sec per SMC generation on the
Lotka-Volterra ODE config (4 params, AdaptivePNormDistance, MedianEpsilon).
``vs_baseline`` compares against the reference-architecture baseline measured
on THIS machine: the same statistical configuration run through the scalar
host path (``SingleCoreSampler`` over the reference-faithful closure) — the
reference's MulticoreEvalParallelSampler is that same scalar loop times
core-count; we measure 1-core and scale by the advertised cores to be fair
to the reference (see BASELINE.md).
"""
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "tpu,cpu")

import numpy as np


def run_tpu_bench(pop_size: int = 2000, n_gens: int = 6, seed: int = 0):
    import jax

    import pyabc_tpu as pt
    from pyabc_tpu.models import lotka_volterra as lv

    model = lv.make_lv_model()
    prior = lv.default_prior()
    obs = lv.observed_data(seed=123)

    abc = pt.ABCSMC(
        model, prior,
        pt.AdaptivePNormDistance(p=2),
        population_size=pop_size,
        eps=pt.MedianEpsilon(),
        seed=seed,
    )
    abc.new("sqlite://", obs)
    t0 = time.time()
    h = abc.run(max_nr_populations=n_gens + 2)
    total = time.time() - t0
    # steady-state throughput: gen 0 carries the prior-kernel compile and
    # gen 1 the transition-kernel compile (both one-offs); time gens 2..N
    # from the per-generation end times recorded in History
    pops = h.get_all_populations()
    pops = pops[pops.t >= 0]
    import pandas as pd

    ends = pd.to_datetime(pops["population_end_time"])
    gens = len(ends) - 2
    elapsed = (ends.iloc[-1] - ends.iloc[1]).total_seconds()
    accepted = pop_size * max(gens, 1)
    pps = accepted / max(elapsed, 1e-9)
    return pps, dict(total_s=round(total, 2), bench_s=round(elapsed, 2),
                     generations=gens, pop_size=pop_size,
                     total_sims=int(h.total_nr_simulations))


def run_host_baseline(pop_size: int = 60, n_gens: int = 2, seed: int = 0,
                      assumed_cores: int = 8):
    """Reference-architecture throughput on this machine (scalar closure
    path, scaled by assumed_cores as an upper bound on
    MulticoreEvalParallelSampler)."""
    import jax

    import pyabc_tpu as pt
    from pyabc_tpu.models import lotka_volterra as lv

    model = lv.make_lv_model()
    prior = lv.default_prior()
    obs = lv.observed_data(seed=123)
    np.random.seed(seed)
    abc = pt.ABCSMC(
        model, prior, pt.PNormDistance(p=2), population_size=pop_size,
        eps=pt.QuantileEpsilon(initial_epsilon=200.0, alpha=0.5),
        sampler=pt.SingleCoreSampler(),
    )
    abc.new("sqlite://", obs)
    t0 = time.time()
    h = abc.run(max_nr_populations=n_gens)
    elapsed = time.time() - t0
    accepted = pop_size * h.n_populations
    return accepted / elapsed * assumed_cores


def main():
    if os.environ.get("PYABC_TPU_BENCH_CPU"):
        # local verification: force the CPU platform (under axon the TPU
        # tunnel ignores JAX_PLATFORMS and would dominate wall time)
        import jax

        jax.config.update("jax_default_device", jax.devices("cpu")[0])
    pop = int(os.environ.get("PYABC_TPU_BENCH_POP", 2000))
    gens = int(os.environ.get("PYABC_TPU_BENCH_GENS", 6))
    pps, info = run_tpu_bench(pop_size=pop, n_gens=gens)
    baseline_file = os.path.join(os.path.dirname(__file__), ".baseline_pps")
    if os.path.exists(baseline_file):
        baseline = float(open(baseline_file).read().strip())
    else:
        baseline = run_host_baseline()
        with open(baseline_file, "w") as fh:
            fh.write(str(baseline))
    print(json.dumps({
        "metric": "accepted_particles_per_sec_lotka_volterra",
        "value": round(pps, 1),
        "unit": "particles/s",
        "vs_baseline": round(pps / baseline, 2),
        **info,
        "baseline_particles_per_sec": round(baseline, 1),
    }))


if __name__ == "__main__":
    main()
