"""Micro-profile one LV generation: kernel vs transfer vs host adaptation.

Not part of the package — a diagnostic harness for BASELINE.md's gap
analysis. Run on the real chip (default) or CPU (JAX_PLATFORMS=cpu).
"""
import time

import numpy as np


def main():
    import jax

    import pyabc_tpu as pt
    from pyabc_tpu.models import lotka_volterra as lv

    model = lv.make_lv_model()
    prior = lv.default_prior()
    obs = lv.observed_data(seed=123)

    abc = pt.ABCSMC(
        model, prior, pt.AdaptivePNormDistance(p=2),
        population_size=1000, eps=pt.MedianEpsilon(), seed=0,
    )
    abc.new("sqlite://", obs)
    print("platform:", jax.devices()[0].platform)

    # run 3 generations to reach steady state (transition kernel compiled)
    h = abc.run(max_nr_populations=3)
    for t in range(h.max_t + 1):
        print(f"t={t} telemetry:", h.get_telemetry(t))

    # now profile one more generation by hand, split into stages
    t = h.max_t + 1
    sampler = abc.sampler
    n_t = 1000
    for rep in range(3):
        t0 = time.perf_counter()
        spec = abc._generation_spec(t)
        t_spec = time.perf_counter()
        handle = sampler.dispatch(n_t, spec, t)
        t_dispatch = time.perf_counter()
        # block until device compute done (sync on a scalar, cheap transfer)
        out = handle["out"]
        n_acc = int(out["n_acc"])  # forces kernel completion, 4-byte fetch
        rounds = int(out["rounds"])
        t_kernel = time.perf_counter()
        sample = sampler.collect(handle)
        t_fetch = time.perf_counter()
        pop = abc._sample_to_population(sample)
        nr_evals = sampler.nr_evaluations_
        abc._adapt_components(t, sample, pop, abc.eps(t), n_t / nr_evals)
        t_adapt = time.perf_counter()
        print(
            f"rep{rep}: spec={t_spec-t0:.4f}s dispatch={t_dispatch-t_spec:.4f}s "
            f"kernel={t_kernel-t_dispatch:.4f}s collect={t_fetch-t_kernel:.4f}s "
            f"adapt={t_adapt-t_fetch:.4f}s "
            f"| rounds={rounds} n_acc={n_acc} B={sampler._last_B}"
        )
        t += 1


if __name__ == "__main__":
    main()
