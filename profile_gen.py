"""Micro-profile one LV generation: kernel vs transfer vs host adaptation.

Not part of the package — a diagnostic harness for BASELINE.md's gap
analysis. Run on the real chip (default) or CPU (JAX_PLATFORMS=cpu).

Round 7: the harness is parameterized (``--pop``, ``--transition``,
``--generations``) so the scale-path refit kernel can be reproduced
standalone — the round-5 pop-16384 LocalTransition case was previously
unreachable here — and ``--profile-refit`` compiles the refit variants
WITHOUT running SMC and prints XLA cost-analysis FLOP counts plus
compiled sort-op counts:

    JAX_PLATFORMS=cpu python profile_gen.py --profile-refit --pop 16384

This is the CPU proxy for the scale-lane acceptance: per-generation
refit cost with the cadence engine (drift statistic every generation +
one threshold-selection refit every m generations) vs the old
unconditional top_k refit, measured on the compiled programs.
"""
import argparse
import json
import time

import numpy as np


def _cost(compiled) -> dict:
    """Normalize compiled.cost_analysis() across jax versions (dict, or
    one dict per device)."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca or {})


def _sort_ops(compiled) -> int:
    """Count sort / top-k ops in the compiled HLO — the data-dependent
    permutation work the threshold selection exists to eliminate (lowered
    as ``sort`` on TPU, a ``TopK`` custom call on CPU)."""
    import re

    try:
        txt = compiled.as_text()
    except Exception:
        return -1
    return len(re.findall(r"= sort\(|sort\.[0-9]+ = |TopK", txt))


def profile_refit(pop: int, dim: int, k_fraction: float, refit_every: int,
                  time_execs: bool) -> dict:
    """Compile (and optionally run) the LocalTransition refit variants at
    the requested population and print the amortization table."""
    import jax
    import jax.numpy as jnp

    import pyabc_tpu as pt
    from pyabc_tpu.transition.util import device_proposal_drift

    tr = pt.LocalTransition(k_fraction=k_fraction)
    k_cap = tr._effective_k(pop, dim)
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.normal(size=(pop, dim)), jnp.float32)
    w = jnp.full((pop,), 1.0 / pop, jnp.float32)
    vmask = jnp.ones((dim,), jnp.float32)

    def fit(selection):
        return lambda X_, w_: pt.LocalTransition.device_fit(
            X_, w_, dim=dim, scaling=1.0, k_cap=k_cap,
            k_fraction=k_fraction, selection=selection,
        )

    def drift_fn(X_, w_):
        return device_proposal_drift(X_, w_, X_ + 0.1, w_, vmask)

    variants = {
        "refit_topk": fit("topk"),
        "refit_threshold": fit("threshold"),
        "drift_stat": drift_fn,
    }
    report = {"pop": pop, "dim": dim, "k_cap": k_cap,
              "refit_every": refit_every, "variants": {}}
    for name, fn in variants.items():
        compiled = jax.jit(fn).lower(X, w).compile()
        ca = _cost(compiled)
        entry = {
            "flops": float(ca.get("flops", 0.0)),
            "transcendentals": float(ca.get("transcendentals", 0.0)),
            "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
            "sort_ops": _sort_ops(compiled),
        }
        if time_execs:
            out = compiled(X, w)
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            jax.block_until_ready(compiled(X, w))
            entry["exec_s"] = round(time.perf_counter() - t0, 4)
        report["variants"][name] = entry
        print(f"{name:>18}: flops={entry['flops']:.3e} "
              f"sort_ops={entry['sort_ops']} "
              f"bytes={entry['bytes_accessed']:.3e}"
              + (f" exec_s={entry['exec_s']}" if time_execs else ""))

    f_topk = report["variants"]["refit_topk"]["flops"]
    f_thr = report["variants"]["refit_threshold"]["flops"]
    f_drift = report["variants"]["drift_stat"]["flops"]
    # per-generation refit cost: baseline = one top_k refit EVERY
    # generation; cadence = the drift statistic every generation plus one
    # threshold refit amortized over refit_every generations
    amortized = f_drift + f_thr / max(refit_every, 1)
    report["per_gen_flops_every_gen_topk"] = f_topk
    report["per_gen_flops_cadence"] = amortized
    report["refit_cost_reduction_x"] = round(f_topk / max(amortized, 1.0), 2)
    report["sort_ops_eliminated"] = (
        report["variants"]["refit_topk"]["sort_ops"]
        - report["variants"]["refit_threshold"]["sort_ops"]
    )
    print(
        f"per-generation refit cost: every-gen top_k {f_topk:.3e} flops "
        f"vs cadence(m={refit_every}) {amortized:.3e} flops "
        f"-> {report['refit_cost_reduction_x']}x reduction; "
        f"sort ops {report['variants']['refit_topk']['sort_ops']} -> "
        f"{report['variants']['refit_threshold']['sort_ops']}"
    )
    print("PROFILE_REFIT " + json.dumps(report))
    return report


def build_scenario(model_name: str, segments: int | None):
    """(models, priors, observation, distance) for the profiling run —
    the refit/segment harness drives the scenario kernels, not just LV
    (ISSUE 15 satellite). Segmented construction (where supported) lets
    ``--early-reject`` profile the segment-inner proposal loop."""
    import pyabc_tpu as pt

    if model_name == "lv":
        from pyabc_tpu.models import lotka_volterra as lv

        return (lv.make_lv_model(), lv.default_prior(),
                lv.observed_data(seed=123), pt.AdaptivePNormDistance(p=2))
    if model_name == "gillespie":
        from pyabc_tpu.models import gillespie as g

        seg = {"segments": segments} if segments else {}
        return (g.make_birth_death_model(**seg), g.birth_death_prior(),
                g.observed_birth_death(**seg), pt.PNormDistance(p=2))
    if model_name == "sir":
        from pyabc_tpu.models import sir

        seg = {"segments": segments} if segments else {}
        return (sir.make_network_sir_model(**seg), sir.network_sir_prior(),
                sir.observed_network_sir(**seg), pt.PNormDistance(p=2))
    if model_name == "model_selection":
        from pyabc_tpu.models import model_selection as msel

        models, priors, _ts = msel.ode_family(segments=segments)
        obs = msel.observed_ode_family(seed=0, segments=segments)
        return models, priors, obs, pt.PNormDistance(p=2)
    raise ValueError(f"unknown --model {model_name!r}")


def main(pop: int = 1000, transition: str = "mvn", generations: int = 3,
         k_fraction: float = 0.25, refit_every: int | None = None,
         model_name: str = "lv", segments: int | None = None,
         early_reject: str = "auto", sharded: int | None = None,
         sumstat: str = "identity"):
    import jax

    import pyabc_tpu as pt

    model, prior, obs, distance = build_scenario(model_name, segments)

    fit_times: list = []
    if sumstat != "identity":
        # learned summaries (ISSUE 20): override the scenario distance
        # with plain PNorm + PredictorSumstat — the config the device-fit
        # plan (and the segmented transformed bound) serves; host fits
        # are timed through the wrapper, device boundary refits are
        # counted from the run's metrics
        pred = (pt.LinearPredictor() if sumstat == "linear"
                else pt.MLPPredictor(hidden=(32,), n_steps=200))
        _orig_fit = pred.fit

        def _timed_fit(x, y, w=None):
            t0 = time.perf_counter()
            _orig_fit(x, y, w)
            fit_times.append(time.perf_counter() - t0)

        pred.fit = _timed_fit
        distance = pt.PNormDistance(p=2, sumstat=pt.PredictorSumstat(pred))

    from pyabc_tpu.observability.metrics import MetricsRegistry

    metrics = MetricsRegistry()

    trans = (pt.LocalTransition(k_fraction=k_fraction)
             if transition == "local" else None)
    abc = pt.ABCSMC(
        model, prior, distance,
        population_size=pop, eps=pt.MedianEpsilon(), seed=0,
        metrics=metrics,
        early_reject={"auto": "auto", "on": True,
                      "off": False}[early_reject],
        **({"sharded": sharded} if sharded else {}),
        **({"transitions": trans} if trans is not None else {}),
        **({"refit_every": refit_every} if refit_every is not None else {}),
    )
    abc.new("sqlite://", obs)
    print("platform:", jax.devices()[0].platform)

    # run `generations` generations to reach steady state (kernel compiled)
    h = abc.run(max_nr_populations=generations)
    for t in range(h.max_t + 1):
        print(f"t={t} telemetry:", h.get_telemetry(t))
    if abc.refit_events:
        refits = sum(1 for _t, r, _d, _c in abc.refit_events if r)
        print(f"refit events: {refits}/{len(abc.refit_events)} "
              f"generations refit; last drift "
              f"{abc.refit_events[-1][2]:.4f}")
    if sumstat != "identity":
        from pyabc_tpu.observability.metrics import SUMSTAT_REFITS_TOTAL

        ss = abc.distance_function.sumstat
        plan = abc._sumstat_device_plan
        S = abc.spec.total_size
        C2 = ss.out_dim(S)
        dev_refits = abc.metrics.counter(SUMSTAT_REFITS_TOTAL).value
        print(
            f"SUMSTAT kind={sumstat} "
            f"mode={'device' if plan is not None else 'host'} "
            f"C={S} C'={C2} reduction={S / max(C2, 1):.1f}x "
            f"host_fits={len(fit_times)} "
            f"host_fit_s={sum(fit_times):.4f} "
            f"device_refits={int(dev_refits)}"
        )

    # now profile one more generation by hand, split into stages
    t = h.max_t + 1
    sampler = abc.sampler
    n_t = pop
    for rep in range(3):
        t0 = time.perf_counter()
        spec = abc._generation_spec(t)
        t_spec = time.perf_counter()
        handle = sampler.dispatch(n_t, spec, t)
        t_dispatch = time.perf_counter()
        # block until device compute done (sync on a scalar, cheap transfer)
        out = handle["out"]
        n_acc = int(out["n_acc"])  # forces kernel completion, 4-byte fetch
        rounds = int(out["rounds"])
        t_kernel = time.perf_counter()
        sample = sampler.collect(handle)
        t_fetch = time.perf_counter()
        pop_obj = abc._sample_to_population(sample)
        nr_evals = sampler.nr_evaluations_
        abc._adapt_components(t, sample, pop_obj, abc.eps(t), n_t / nr_evals)
        t_adapt = time.perf_counter()
        print(
            f"rep{rep}: spec={t_spec-t0:.4f}s dispatch={t_dispatch-t_spec:.4f}s "
            f"kernel={t_kernel-t_dispatch:.4f}s collect={t_fetch-t_kernel:.4f}s "
            f"adapt={t_adapt-t_fetch:.4f}s "
            f"| rounds={rounds} n_acc={n_acc} B={sampler._last_B}"
        )
        t += 1


if __name__ == "__main__":
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--pop", type=int, default=1000,
                    help="population size (16384 reproduces the r5 scale "
                         "case)")
    ap.add_argument("--model",
                    choices=("lv", "gillespie", "sir", "model_selection"),
                    default="lv",
                    help="scenario kernel to profile (gillespie = "
                         "tau-leap birth-death, sir = network SIR, "
                         "model_selection = K=3 ODE family)")
    ap.add_argument("--segments", type=int, default=None,
                    help="segmented construction (early-reject protocol) "
                         "for scenario models that support it")
    ap.add_argument("--early-reject", choices=("auto", "on", "off"),
                    default="auto",
                    help="segmented early-reject mode for the SMC run")
    ap.add_argument("--sharded", type=int, default=None,
                    help="shard count for the sharded fused kernel "
                         "(virtual shards on one device, or a mesh "
                         "width that divides it); composes with "
                         "--segments --early-reject (ISSUE 17)")
    ap.add_argument("--sumstat", choices=("identity", "linear", "mlp"),
                    default="identity",
                    help="learned summary statistics (ISSUE 20): wrap "
                         "the distance in a PredictorSumstat; composes "
                         "with --sharded/--segments/--early-reject and "
                         "prints fit wall time + C->C' reduction")
    ap.add_argument("--transition", choices=("mvn", "local"), default="mvn")
    ap.add_argument("--generations", type=int, default=3)
    ap.add_argument("--k-fraction", type=float, default=0.25)
    ap.add_argument("--refit-every", type=int, default=None,
                    help="LocalTransition refit cadence (None = auto)")
    ap.add_argument("--profile-refit", action="store_true",
                    help="compile-and-count the refit variants only "
                         "(no SMC run): the CPU FLOP/op proxy")
    ap.add_argument("--dim", type=int, default=4,
                    help="parameter dim for --profile-refit")
    ap.add_argument("--time", action="store_true",
                    help="also execute + wall-time the compiled variants")
    args = ap.parse_args()
    if args.profile_refit:
        profile_refit(args.pop, args.dim, args.k_fraction,
                      args.refit_every or 16, args.time)
    else:
        main(pop=args.pop, transition=args.transition,
             generations=args.generations, k_fraction=args.k_fraction,
             refit_every=args.refit_every, model_name=args.model,
             segments=args.segments, early_reject=args.early_reject,
             sharded=args.sharded, sumstat=args.sumstat)
