"""Regression predictors for learned summary statistics.

Reference parity: ``pyabc/predictor/predictor.py::{Predictor,
LinearPredictor, LassoPredictor, GPPredictor, MLPPredictor,
ModelSelectionPredictor}`` (SURVEY.md §2.2 last row) — the regression
models behind Fearnhead-Prangle learned statistics: fit theta ~ f(x) on
recorded simulations, then use s(x) = f(x) as the summary statistic.

TPU-first: fitting runs host-side once per generation (small problems), but
every predictor exposes a TRACEABLE ``device_predict(x, params)`` +
``device_params()`` pair so the learned transform itself executes inside
the jitted generation kernel — per-generation refits swap array arguments,
never recompile.
"""
from __future__ import annotations

from abc import ABC, abstractmethod

import jax
import jax.numpy as jnp
import numpy as np

from ..observability.sync import NULL_SYNC_LEDGER


def _standardize_fit(x: np.ndarray):
    mu = x.mean(axis=0)
    sd = x.std(axis=0)
    sd = np.where(sd > 1e-12, sd, 1.0)
    return mu, sd


class Predictor(ABC):
    """y ~ f(x) regression with a traceable predict path."""

    #: the owning run's SyncLedger (rebound by ``ABCSMC.run``): a fit
    #: that trains on-device and fetches the result back blocks the host
    #: and must account the round trip; outside a run the null ledger
    #: swallows the record
    sync_ledger = NULL_SYNC_LEDGER

    @abstractmethod
    def fit(self, x: np.ndarray, y: np.ndarray,
            w: np.ndarray | None = None) -> None:
        """Fit on (n, S) inputs and (n, d) targets, optional weights."""

    @abstractmethod
    def predict(self, x: np.ndarray) -> np.ndarray:
        """(n, S) or (S,) -> (n, d) or (d,). Host path."""

    @property
    @abstractmethod
    def fitted(self) -> bool: ...

    # ------------------------------------------------------------- device
    def is_device_compatible(self) -> bool:
        return True

    @abstractmethod
    def device_params(self):
        """Pytree of jnp arrays representing the fitted transform."""

    @abstractmethod
    def device_predict(self, x, params):
        """Traceable: (S,) flat vector + params pytree -> (d,) prediction."""

    def __repr__(self):
        return f"{type(self).__name__}()"


class LinearPredictor(Predictor):
    """Weighted ridge regression (reference LinearPredictor).

    Closed form W = (X'ΛX + αI)^-1 X'Λ y on standardized inputs.
    """

    def __init__(self, alpha: float = 1e-6, normalize: bool = True):
        self.alpha = float(alpha)
        self.normalize = normalize
        self._W = None  # (S, d)
        self._b = None  # (d,)
        self._mu = None
        self._sd = None

    @property
    def fitted(self) -> bool:
        return self._W is not None

    def fit(self, x, y, w=None):
        x = np.asarray(x, np.float64)
        y = np.asarray(y, np.float64)
        if y.ndim == 1:
            y = y[:, None]
        n, S = x.shape
        if self.normalize:
            self._mu, self._sd = _standardize_fit(x)
        else:
            self._mu, self._sd = np.zeros(S), np.ones(S)
        xs = (x - self._mu) / self._sd
        if w is None:
            w = np.ones(n)
        w = np.asarray(w, np.float64) * n / np.sum(w)
        xw = xs * w[:, None]
        A = xs.T @ xw + self.alpha * np.eye(S)
        ym = (w @ y) / n
        B = xs.T @ (w[:, None] * (y - ym))
        self._W = np.linalg.solve(A, B)
        self._b = ym

    def predict(self, x):
        x = np.asarray(x, np.float64)
        single = x.ndim == 1
        xs = (np.atleast_2d(x) - self._mu) / self._sd
        out = xs @ self._W + self._b
        return out[0] if single else out

    def device_params(self):
        return {
            "W": jnp.asarray(self._W, jnp.float32),
            "b": jnp.asarray(self._b, jnp.float32),
            "mu": jnp.asarray(self._mu, jnp.float32),
            "sd": jnp.asarray(self._sd, jnp.float32),
        }

    def device_predict(self, x, params):
        xs = (x - params["mu"]) / params["sd"]
        return xs @ params["W"] + params["b"]


class LassoPredictor(LinearPredictor):
    """L1-regularized linear regression via ISTA (reference LassoPredictor,
    sklearn Lasso there; here a dependency-free proximal gradient solve).
    Shares the linear device transform."""

    def __init__(self, alpha: float = 0.01, n_iter: int = 500,
                 normalize: bool = True):
        super().__init__(alpha=alpha, normalize=normalize)
        self.n_iter = int(n_iter)

    def fit(self, x, y, w=None):
        x = np.asarray(x, np.float64)
        y = np.asarray(y, np.float64)
        if y.ndim == 1:
            y = y[:, None]
        n, S = x.shape
        if self.normalize:
            self._mu, self._sd = _standardize_fit(x)
        else:
            self._mu, self._sd = np.zeros(S), np.ones(S)
        xs = (x - self._mu) / self._sd
        ym = y.mean(axis=0)
        yc = y - ym
        # ISTA: step 1/L with L = largest eigenvalue of X'X/n
        gram = xs.T @ xs / n
        L = float(np.linalg.eigvalsh(gram)[-1]) + 1e-12
        step = 1.0 / L
        W = np.zeros((S, yc.shape[1]))
        thr = self.alpha * step
        xty = xs.T @ yc / n
        for _ in range(self.n_iter):
            grad = gram @ W - xty
            W = W - step * grad
            W = np.sign(W) * np.maximum(np.abs(W) - thr, 0.0)
        self._W = W
        self._b = ym


class MLPPredictor(Predictor):
    """Small MLP regressor trained with Adam (reference MLPPredictor;
    jax-native instead of sklearn MLPRegressor)."""

    def __init__(self, hidden: tuple = (64, 64), n_steps: int = 400,
                 lr: float = 1e-3, seed: int = 0):
        self.hidden = tuple(hidden)
        self.n_steps = int(n_steps)
        self.lr = float(lr)
        self.seed = int(seed)
        self._params = None
        self._mu = self._sd = None
        self._ymu = self._ysd = None

    @property
    def fitted(self) -> bool:
        return self._params is not None

    def _init_params(self, key, sizes):
        params = []
        for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
            key, sub = jax.random.split(key)
            scale = np.sqrt(2.0 / fan_in)
            params.append({
                "w": jax.random.normal(sub, (fan_in, fan_out)) * scale,
                "b": jnp.zeros((fan_out,)),
            })
        return params

    @staticmethod
    def _forward(params, x):
        h = x
        for layer in params[:-1]:
            h = jnp.tanh(h @ layer["w"] + layer["b"])
        last = params[-1]
        return h @ last["w"] + last["b"]

    def fit(self, x, y, w=None):
        import optax

        x = np.asarray(x, np.float64)
        y = np.asarray(y, np.float64)
        if y.ndim == 1:
            y = y[:, None]
        self._mu, self._sd = _standardize_fit(x)
        self._ymu, self._ysd = _standardize_fit(y)
        xs = jnp.asarray((x - self._mu) / self._sd, jnp.float32)
        ys = jnp.asarray((y - self._ymu) / self._ysd, jnp.float32)
        wts = jnp.asarray(
            np.ones(len(x)) if w is None else w / np.mean(w), jnp.float32
        )
        sizes = (x.shape[1], *self.hidden, y.shape[1])
        params = self._init_params(jax.random.key(self.seed), sizes)
        opt = optax.adam(self.lr)
        opt_state = opt.init(params)

        def loss_fn(p):
            pred = self._forward(p, xs)
            return jnp.mean(wts[:, None] * (pred - ys) ** 2)

        @jax.jit
        def train(params, opt_state):
            def step(carry, _):
                p, s = carry
                g = jax.grad(loss_fn)(p)
                updates, s = opt.update(g, s)
                p = optax.apply_updates(p, updates)
                return (p, s), ()

            (params, opt_state), _ = jax.lax.scan(
                step, (params, opt_state), None, length=self.n_steps
            )
            return params

        self._params = jax.device_get(train(params, opt_state))
        self.sync_ledger.record(
            "sumstat_train_fetch",
            sum(int(np.asarray(v).nbytes)
                for v in jax.tree.leaves(self._params)))

    def predict(self, x):
        x = np.asarray(x, np.float64)
        single = x.ndim == 1
        xs = (np.atleast_2d(x) - self._mu) / self._sd
        out = np.asarray(
            self._forward(
                jax.tree.map(jnp.asarray, self._params),
                jnp.asarray(xs, jnp.float32),
            )
        )
        out = out * self._ysd + self._ymu
        return out[0] if single else out

    def device_params(self):
        return {
            "layers": jax.tree.map(
                lambda v: jnp.asarray(v, jnp.float32), self._params
            ),
            "mu": jnp.asarray(self._mu, jnp.float32),
            "sd": jnp.asarray(self._sd, jnp.float32),
            "ymu": jnp.asarray(self._ymu, jnp.float32),
            "ysd": jnp.asarray(self._ysd, jnp.float32),
        }

    def device_predict(self, x, params):
        xs = (x - params["mu"]) / params["sd"]
        out = self._forward(params["layers"], xs)
        return out * params["ysd"] + params["ymu"]


class GPPredictor(Predictor):
    """RBF kernel-ridge regression (reference GPPredictor; exact GP mean).

    Training points are subsampled to ``cap`` and ZERO-PADDED to a static
    size so the device transform k(x, X_train) @ alpha keeps one compiled
    shape across generations (padded rows get alpha = 0: exact no-op).
    """

    def __init__(self, length_scale: float | None = None, alpha: float = 1e-4,
                 cap: int = 512, seed: int = 0):
        self.length_scale = length_scale
        self.alpha = float(alpha)
        self.cap = int(cap)
        self.seed = int(seed)
        self._X = None       # (cap, S) padded
        self._alpha_w = None  # (cap, d) padded
        self._ls = None
        self._mu = self._sd = None
        self._ymu = None

    @property
    def fitted(self) -> bool:
        return self._X is not None

    def fit(self, x, y, w=None):
        x = np.asarray(x, np.float64)
        y = np.asarray(y, np.float64)
        if y.ndim == 1:
            y = y[:, None]
        rng = np.random.default_rng(self.seed)
        n = len(x)
        if n > self.cap:
            idx = rng.choice(n, self.cap, replace=False)
            x, y = x[idx], y[idx]
            n = self.cap
        self._mu, self._sd = _standardize_fit(x)
        xs = (x - self._mu) / self._sd
        self._ymu = y.mean(axis=0)
        yc = y - self._ymu
        if self.length_scale is None:
            # median heuristic on pairwise distances
            d2 = ((xs[:, None] - xs[None, :]) ** 2).sum(-1)
            med = np.median(d2[d2 > 0]) if (d2 > 0).any() else 1.0
            self._ls = float(np.sqrt(med / 2.0) + 1e-12)
        else:
            self._ls = float(self.length_scale)
        K = np.exp(-((xs[:, None] - xs[None, :]) ** 2).sum(-1)
                   / (2 * self._ls**2))
        a = np.linalg.solve(K + self.alpha * np.eye(n), yc)
        # zero-pad to the static cap (alpha rows of 0 contribute nothing)
        S, d = xs.shape[1], yc.shape[1]
        Xp = np.zeros((self.cap, S))
        ap = np.zeros((self.cap, d))
        Xp[:n] = xs
        ap[:n] = a
        self._X, self._alpha_w = Xp, ap

    def predict(self, x):
        x = np.asarray(x, np.float64)
        single = x.ndim == 1
        xs = (np.atleast_2d(x) - self._mu) / self._sd
        K = np.exp(-((xs[:, None] - self._X[None, :]) ** 2).sum(-1)
                   / (2 * self._ls**2))
        out = K @ self._alpha_w + self._ymu
        return out[0] if single else out

    def device_params(self):
        return {
            "X": jnp.asarray(self._X, jnp.float32),
            "a": jnp.asarray(self._alpha_w, jnp.float32),
            "ls": jnp.asarray(self._ls, jnp.float32),
            "mu": jnp.asarray(self._mu, jnp.float32),
            "sd": jnp.asarray(self._sd, jnp.float32),
            "ymu": jnp.asarray(self._ymu, jnp.float32),
        }

    def device_predict(self, x, params):
        xs = (x - params["mu"]) / params["sd"]
        k = jnp.exp(-jnp.sum((xs[None, :] - params["X"]) ** 2, axis=-1)
                    / (2 * params["ls"] ** 2))
        return k @ params["a"] + params["ymu"]


class ModelSelectionPredictor(Predictor):
    """Pick the best of several predictors by validation MSE (reference
    ModelSelectionPredictor)."""

    def __init__(self, predictors: list, split: float = 0.2, seed: int = 0):
        self.predictors = list(predictors)
        self.split = float(split)
        self.seed = int(seed)
        self.chosen: Predictor | None = None

    @property
    def fitted(self) -> bool:
        return self.chosen is not None and self.chosen.fitted

    def fit(self, x, y, w=None):
        x = np.asarray(x, np.float64)
        y = np.asarray(y, np.float64)
        if y.ndim == 1:
            y = y[:, None]
        rng = np.random.default_rng(self.seed)
        n = len(x)
        perm = rng.permutation(n)
        n_val = max(int(n * self.split), 1)
        val, train = perm[:n_val], perm[n_val:]
        best, best_mse = None, np.inf
        skipped: list[str] = []
        for p in self.predictors:
            try:
                p.fit(x[train], y[train],
                      None if w is None else np.asarray(w)[train])
                mse = float(np.mean((p.predict(x[val]) - y[val]) ** 2))
            except Exception as err:
                # singular fits etc. legitimately disqualify a candidate,
                # but never silently (EXC001): keep the trace for the
                # all-candidates-failed error below
                skipped.append(f"{type(p).__name__}: {err!r}")
                continue
            if mse < best_mse:
                best, best_mse = p, mse
        if best is None:
            raise RuntimeError(
                "no predictor could be fit; candidates failed with: "
                + "; ".join(skipped))
        best.fit(x, y, w)  # refit the winner on everything
        self.chosen = best

    def predict(self, x):
        return self.chosen.predict(x)

    def is_device_compatible(self):
        return all(p.is_device_compatible() for p in self.predictors)

    def device_params(self):
        return self.chosen.device_params()

    def device_predict(self, x, params):
        # NOTE: the chosen predictor class is baked into the trace; a change
        # of winner across generations retriggers one compile (rare, cheap)
        return self.chosen.device_predict(x, params)

    def __repr__(self):
        return f"ModelSelectionPredictor({self.predictors!r})"
