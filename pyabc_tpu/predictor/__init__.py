"""Regression predictors for learned summary statistics
(reference ``pyabc/predictor/``)."""
from .predictor import (
    GPPredictor,
    LassoPredictor,
    LinearPredictor,
    MLPPredictor,
    ModelSelectionPredictor,
    Predictor,
)

__all__ = [
    "Predictor",
    "LinearPredictor",
    "LassoPredictor",
    "MLPPredictor",
    "GPPredictor",
    "ModelSelectionPredictor",
]
