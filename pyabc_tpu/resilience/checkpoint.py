"""Mid-chunk device checkpointing for the fused multigen loop.

``ABCSMC.load`` resumes at GENERATION granularity from the History db —
it replays a HOST transition fit on the last stored population and
restarts the chunk chain from a host-built carry. That loses everything
that only lives in the device carry chain: the in-kernel fitted-proposal
params (PR 3's refit cadence means they are NOT a fresh fit of the last
population), the generations-since-refit counter, the stochastic
pdf-norm / Daly-contraction state, and the epsilon running-minimum. This
module checkpoints the ACTUAL carry: after a processed chunk the loop
fetches the chunk's final on-device carry, serializes every array leaf
through :mod:`pyabc_tpu.storage.bytes_storage` (``np.save`` bytes —
dtype + shape preserved, bit-exact for f32/int32/bool and raw PRNG key
data), and atomically renames it into place. A killed orchestrator
resumes by decoding the carry and dispatching the next chunk from it —
the resumed trajectory is BIT-IDENTICAL to the uninterrupted run (the
kernel is deterministic in (root_key, t, carry)).

What a checkpoint does NOT capture (documented deviation, mirrors the
reference's §5.4 adaptive-state caveats): host-side sumstat-predictor
state (sumstat-refit mode rebuilds its carry at chunk boundaries anyway
and is excluded from checkpointing), and the History rows themselves —
the loop flushes the async writer BEFORE each save, so the db is always
at-or-ahead-of the checkpoint's generation and resume never leaves a
History gap (``History.prune_from`` trims any rows past the checkpoint
so re-run generations are not double-persisted).

Atomicity: write to ``<path>.tmp`` + fsync + ``os.replace`` — a crash
mid-save leaves the previous checkpoint intact, never a torn file.

Integrity (round 10): every payload is framed by a fixed header —
``magic | schema version | payload CRC32 | payload length`` — verified
BEFORE unpickling. A truncated file, a flipped bit, or a
schema-version mismatch raises a typed :class:`CheckpointCorruptError`
naming what failed, instead of an opaque ``pickle``/``np.load`` crash
deep in deserialization (or, worse, a silently wrong carry). Resume
catches it and falls back to generation-granularity History replay —
corruption degrades durability, never correctness.
"""
from __future__ import annotations

import os
import pickle
import struct
import zlib

import numpy as np

from ..observability import NULL_METRICS, NULL_TRACER, SYSTEM_CLOCK
from ..observability.metrics import CHECKPOINTS_WRITTEN_TOTAL
from ..storage.bytes_storage import np_from_bytes, np_to_bytes

#: bumped when the on-disk layout changes; loaders reject other versions
#: with CheckpointCorruptError (v2: CRC/length header added, fused carry
#: gained the health-guard stall state; v3: learned-sumstat runs under a
#: device-fit plan checkpoint at all — their dist_w slot carries the
#: fitted predictor pytree ``{"w", "ss"}``, a structure no v2 loader
#: ever rebuilt)
CHECKPOINT_VERSION = 3

#: file magic: identifies a framed pyabc_tpu checkpoint before any parse
CHECKPOINT_MAGIC = b"PTCK"
#: header layout: magic (4s) | schema version (u32) | payload crc32
#: (u32) | payload length (u64) — little-endian, fixed 20 bytes
_HEADER = struct.Struct("<4sIIQ")

_ND = "__nd__"


class CheckpointCorruptError(RuntimeError):
    """A checkpoint file failed integrity verification (bad magic,
    schema-version mismatch, truncation, or CRC mismatch). Carries the
    path and the reason; resume handles it by falling back to the
    History epsilon-trail replay path."""

    def __init__(self, path: str, reason: str):
        super().__init__(f"corrupt checkpoint {path!r}: {reason}")
        self.path = str(path)
        self.reason = str(reason)


def encode_tree(obj):
    """Recursively encode a pytree of containers + array leaves.

    Arrays (numpy or jax — anything ``np.asarray`` accepts) become
    ``{"__nd__": np.save-bytes}``; tuples/lists/dicts keep their
    structure (tuples tagged, so decode restores tuple-vs-list exactly —
    jax carry pytrees are tuple-shaped and lax.scan is strict about it);
    scalars/str/bytes/None pass through. No pickle of user objects:
    the skeleton is plain containers, the leaves are np.save blobs.
    """
    if obj is None or isinstance(obj, (bool, int, float, str, bytes)):
        return obj
    if isinstance(obj, dict):
        return {"__dict__": {k: encode_tree(v) for k, v in obj.items()}}
    if isinstance(obj, tuple):
        return {"__tuple__": [encode_tree(v) for v in obj]}
    if isinstance(obj, list):
        return {"__list__": [encode_tree(v) for v in obj]}
    return {_ND: np_to_bytes(np.asarray(obj))}


def decode_tree(obj):
    """Inverse of :func:`encode_tree`; array leaves come back as numpy
    (bit-exact) — jax consumes numpy leaves directly on dispatch."""
    if obj is None or isinstance(obj, (bool, int, float, str, bytes)):
        return obj
    if isinstance(obj, dict):
        if _ND in obj:
            return np_from_bytes(obj[_ND])
        if "__tuple__" in obj:
            return tuple(decode_tree(v) for v in obj["__tuple__"])
        if "__list__" in obj:
            return [decode_tree(v) for v in obj["__list__"]]
        if "__dict__" in obj:
            return {k: decode_tree(v) for k, v in obj["__dict__"].items()}
    raise ValueError(f"unrecognized checkpoint node: {type(obj)!r}")


class CheckpointManager:
    """Atomic save/load of one checkpoint file at ``path``."""

    def __init__(self, path: str, clock=None, tracer=None, metrics=None):
        self.path = str(path)
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.metrics = metrics if metrics is not None else NULL_METRICS

    def save(self, state: dict) -> int:
        """Encode + atomically persist ``state``; returns bytes written.

        ``state`` is a plain dict whose values may contain arrays at any
        nesting depth (see :func:`encode_tree`); ``version`` and a wall
        timestamp are stamped in here.
        """
        payload = dict(state)
        payload["version"] = CHECKPOINT_VERSION
        payload["saved_wall"] = self.clock.wall()
        blob = pickle.dumps(encode_tree(payload),
                            protocol=pickle.HIGHEST_PROTOCOL)
        header = _HEADER.pack(CHECKPOINT_MAGIC, CHECKPOINT_VERSION,
                              zlib.crc32(blob), len(blob))
        tmp = self.path + ".tmp"
        with self.tracer.span("checkpoint.save", path=self.path,
                              nbytes=len(blob), t=state.get("t")):
            with open(tmp, "wb") as fh:
                fh.write(header)
                fh.write(blob)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, self.path)
        self.metrics.counter(
            CHECKPOINTS_WRITTEN_TOTAL,
            "fused-loop carry checkpoints written",
        ).inc()
        return len(blob)

    def load(self) -> dict | None:
        """The decoded checkpoint, or None when no file exists.

        Integrity failures raise :class:`CheckpointCorruptError` BEFORE
        any unpickling happens (magic -> schema version -> length ->
        CRC32, in that order, so the reason names the outermost
        failure): a truncated or bit-flipped file is diagnosed loudly,
        and the caller (``ABCSMC._maybe_adopt_checkpoint``) falls back
        to the History epsilon-trail replay path."""
        if not os.path.exists(self.path):
            return None
        with self.tracer.span("checkpoint.load", path=self.path):
            with open(self.path, "rb") as fh:
                raw = fh.read()
            if len(raw) < _HEADER.size:
                raise CheckpointCorruptError(
                    self.path, f"file too short for the header "
                    f"({len(raw)} < {_HEADER.size} bytes)")
            magic, version, crc, length = _HEADER.unpack(
                raw[:_HEADER.size])
            if magic != CHECKPOINT_MAGIC:
                raise CheckpointCorruptError(
                    self.path, f"bad magic {magic!r} (not a pyabc_tpu "
                    f"checkpoint, or a pre-header legacy file)")
            if version != CHECKPOINT_VERSION:
                raise CheckpointCorruptError(
                    self.path, f"schema version {version} != supported "
                    f"{CHECKPOINT_VERSION}")
            blob = raw[_HEADER.size:]
            if len(blob) != length:
                raise CheckpointCorruptError(
                    self.path, f"truncated payload: {len(blob)} of "
                    f"{length} bytes")
            if zlib.crc32(blob) != crc:
                raise CheckpointCorruptError(
                    self.path, "payload CRC32 mismatch (bit corruption)")
            try:
                payload = decode_tree(pickle.loads(blob))
            except Exception as exc:
                # CRC passed but the payload does not decode: a writer
                # bug, not wire/disk corruption — still typed, not opaque
                raise CheckpointCorruptError(
                    self.path, f"payload failed to decode: {exc!r}"
                ) from exc
        if not isinstance(payload, dict) \
                or payload.get("version") != CHECKPOINT_VERSION:
            raise CheckpointCorruptError(
                self.path, "decoded payload missing/mismatched version")
        return payload

    def clear(self) -> None:
        """Remove the checkpoint (a cleanly finished run needs none)."""
        for p in (self.path, self.path + ".tmp"):
            try:
                os.remove(p)
            except FileNotFoundError:
                pass


def tree_bit_equal(a, b) -> bool:
    """Structural + bitwise equality of two encoded/decoded pytrees
    (test helper: the round-trip guarantee is BIT-exactness, not
    allclose)."""
    if type(a) is not type(b):
        # bool/int and numpy scalar mixes are NOT tolerated: resume must
        # rebuild exactly what was saved
        return False
    if isinstance(a, dict):
        return a.keys() == b.keys() and all(
            tree_bit_equal(a[k], b[k]) for k in a
        )
    if isinstance(a, (tuple, list)):
        return len(a) == len(b) and all(
            tree_bit_equal(x, y) for x, y in zip(a, b)
        )
    if isinstance(a, np.ndarray):
        return (a.dtype == b.dtype and a.shape == b.shape
                and np.array_equal(
                    a.view(np.uint8) if a.dtype.kind == "V" else a,
                    b.view(np.uint8) if b.dtype.kind == "V" else b,
                    equal_nan=(a.dtype.kind == "f"),
                ))
    return a == b
