"""Deterministic fault injection — kill the system on purpose, on CPU CI.

The reference's elastic path (chrhck/pyABC's Redis sampler) assumes
workers die; this module makes them die ON SCHEDULE so the self-healing
machinery is exercised by every test run instead of only by production
incidents. A :class:`FaultPlan` is a list of :class:`FaultRule`\\ s probed
at named SITES inside the instrumented modules:

==================== =======================================================
site                 probed where
==================== =======================================================
``worker.batch``     elastic worker, mid-batch (before results ship)
``protocol.request`` every broker round trip (inside the retry loop)
``history.persist``  async History writer, before each queued append
``orchestrator.chunk`` fused loop, before processing each fetched chunk
``device.context``   DeviceContext build/reuse (simulated device reset)
``device.carry``     fused loop, each chunk's input carry (numeric
                     corruption — polled, not raised; see below)
``device.mesh``      serving scheduler pump, each tick (mesh topology:
                     ``device_lost`` / ``device_degraded`` — polled;
                     the scheduler applies the loss or cordon)
==================== =======================================================

Rule kinds map to actions: ``kill`` raises :class:`InjectedKill` (hard
worker/orchestrator death — no goodbye, no flush), ``drop`` raises
:class:`InjectedConnectionError` (a ConnectionError subclass, so retry
policies and reconnect loops handle it like a real network blip),
``transient`` / ``error`` raise :class:`InjectedTransientError` /
:class:`InjectedPersistError` (the History writer's two failure
classes), ``reset`` raises :class:`InjectedDeviceReset`, and ``hang`` /
``slow`` / ``delay`` sleep for ``delay_s``.

Numeric-corruption kinds (round 10 health guards): ``nan_poison``,
``cov_corrupt`` and ``weight_zero`` do not raise — silent numerical
failure is exactly the failure mode that never raises. The instrumented
site POLLS for them (:func:`maybe_corrupt`) and applies the corruption
itself (``ops.health.poison_carry`` on the fused chunk carry at
``device.carry``), so every in-kernel health guard is exercised
deterministically on CPU. :func:`maybe_fault` ignores corruption rules;
:func:`maybe_corrupt` ignores raise/sleep rules — one plan can carry
both.

Determinism: probabilistic rules draw from a ``random.Random(seed)``
owned by the plan, and counting rules (``after`` / ``every`` /
``max_fires``) run on per-rule probe counters — the same plan replays
the same fault sequence in every run, which is what lets the fault
matrix assert posterior parity against a seed-matched fault-free run.

Install a plan process-wide with :func:`install_fault_plan`; every
instrumented site calls :func:`maybe_fault`, which is a near-free no-op
when no plan is active (production pays one module-global read).
``abc-worker --fault-plan "worker.batch:kill:after=2"`` installs a
parsed plan in a worker process; the bench ``resilience`` lane does the
same in its mortal-worker subprocesses.

Tenant scoping (round 14, the serving layer): one process now hosts
MANY concurrent runs, but the plan stays process-global — so a rule's
``match`` also tests the calling thread's :func:`fault_scope` tag. The
RunScheduler wraps each tenant's orchestrator thread in
``fault_scope(tenant_id)``, making
``orchestrator.chunk:kill:match=tenant-3`` fire ONLY inside tenant 3's
fault domain even though every tenant probes the same plan — the
containment contract the chaos tests inject against.
"""
from __future__ import annotations

import random
import threading
import time
from contextlib import contextmanager as _contextmanager
from dataclasses import dataclass, field

from ..observability import SYSTEM_CLOCK, global_metrics
from ..observability.metrics import FAULTS_INJECTED_TOTAL


class InjectedFault(Exception):
    """Base for every injected failure; carries its site and kind."""

    def __init__(self, kind: str, site: str, **ctx):
        super().__init__(f"injected fault {kind!r} at {site!r} ({ctx})")
        self.kind = kind
        self.site = site
        self.ctx = ctx


class InjectedKill(InjectedFault):
    """Hard death: the victim stops mid-work, ships nothing, says no bye."""


class InjectedConnectionError(InjectedFault, ConnectionError):
    """A dropped connection — caught wherever real ConnectionErrors are.

    (``InjectedFault`` first in the MRO so its ``__init__`` runs;
    ``ConnectionError`` in the bases so every existing ``except
    ConnectionError`` — retry policies, reconnect loops — handles it
    like a real network blip.)"""


class InjectedTransientError(InjectedFault):
    """A persist failure the writer should retry (db-locked-shaped)."""


class InjectedPersistError(InjectedFault):
    """A persist failure that must latch the writer sticky-dead."""


class InjectedDeviceReset(InjectedFault):
    """A lost device context (TPU preemption / tunnel reset simulation)."""


_KIND_EXC = {
    "kill": InjectedKill,
    "drop": InjectedConnectionError,
    "transient": InjectedTransientError,
    "error": InjectedPersistError,
    "reset": InjectedDeviceReset,
}
_KIND_SLEEP = {"hang": 30.0, "slow": 0.05, "delay": 0.05}
#: numeric-corruption kinds: POLLED by the site (maybe_corrupt), which
#: applies the corruption itself instead of receiving an exception
_KIND_CORRUPT = ("nan_poison", "cov_corrupt", "weight_zero")
#: mesh-topology kinds (round 15, mesh-aware serving): POLLED by the
#: scheduler pump at the ``device.mesh`` site (maybe_device_fault) —
#: ``device_lost`` marks the rule's ``devices`` range dead (leases
#: touching them are reaped, capacity shrinks), ``device_degraded``
#: cordons them (no new placements, existing leases drain naturally),
#: ``host_lost`` (round 18 fleets) kills whole HOSTS — its ``devices``
#: spec names host indices; the scheduler quarantines each host's
#: entire allocator segment. Nothing raises: losing hardware is a
#: scheduler event, not an exception on any tenant's thread.
_KIND_DEVICE = ("device_lost", "device_degraded", "host_lost")
KINDS = (tuple(_KIND_EXC) + tuple(_KIND_SLEEP) + _KIND_CORRUPT
         + _KIND_DEVICE)


@dataclass
class FaultRule:
    """One deterministic fault: fire ``kind`` at ``site``.

    ``after``: skip the first N matching probes. ``every``: of the
    probes past ``after``, fire on every k-th (1 = each). ``p``:
    additionally gate each candidate firing on a seeded coin flip.
    ``max_fires``: stop after N firings (None = unbounded). ``match``:
    substring that must appear in the probe's ``worker_id`` context (so
    one process-global plan can kill only the "mortal" worker).
    ``delay_s``: sleep duration for hang/slow/delay kinds. ``devices``:
    for the mesh-topology kinds, which device indices the event hits —
    ``"3"`` (one device) or ``"4-7"`` (inclusive range).
    """

    site: str
    kind: str
    after: int = 0
    every: int = 1
    p: float = 1.0
    max_fires: int | None = 1
    match: str = ""
    delay_s: float | None = None
    devices: str = ""
    #: probe / fire counters (mutated by the owning plan, under its lock)
    n_probes: int = field(default=0, compare=False)
    n_fires: int = field(default=0, compare=False)

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r} (one of {KINDS})"
            )
        if self.every < 1:
            raise ValueError("every must be >= 1")
        if self.kind in _KIND_DEVICE and not self.devices:
            raise ValueError(
                f"fault kind {self.kind!r} needs a devices= option "
                f"(e.g. devices=3 or devices=4-7)"
            )
        if self.devices:
            self.device_indices()  # validate the spec eagerly

    def device_indices(self) -> list[int]:
        """The device indices a mesh-topology rule hits (``"3"`` or an
        inclusive ``"4-7"`` range)."""
        s = str(self.devices).strip()
        lo, sep, hi = s.partition("-")
        if not sep:
            return [int(s)]
        lo_i, hi_i = int(lo), int(hi)
        if hi_i < lo_i:
            raise ValueError(f"bad devices range {s!r}")
        return list(range(lo_i, hi_i + 1))


#: thread-local fault-domain tag (the serving layer's tenant id); empty
#: outside any scope. Read lock-free per probe.
_SCOPE = threading.local()


@_contextmanager
def fault_scope(tag: str):
    """Tag every probe on the calling thread with a fault-domain id.

    A rule's ``match`` then selects this domain: ``match=tenant-3`` hits
    probes made inside ``fault_scope("tenant-3")`` (or probes whose
    explicit ``worker_id`` ctx matches, as before). Scopes nest; the
    previous tag is restored on exit. Threads the scoped code spawns do
    NOT inherit the tag — a tenant's fault domain is its orchestrator
    thread, which is where every instrumented serving-path site probes.
    """
    prev = getattr(_SCOPE, "tag", "")
    _SCOPE.tag = str(tag)
    try:
        yield
    finally:
        _SCOPE.tag = prev


def current_fault_scope() -> str:
    """The calling thread's fault-domain tag ('' outside any scope)."""
    return getattr(_SCOPE, "tag", "")


class FaultPlan:
    """A seeded, clock-injected set of fault rules probed at named sites."""

    def __init__(self, rules, seed: int = 0, clock=None, sleep=time.sleep,
                 metrics=None):
        self.rules = list(rules)
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self._sleep = sleep
        self._rng = random.Random(seed)
        self._metrics = metrics if metrics is not None else global_metrics()
        self._lock = threading.Lock()
        #: fired-fault event log: {"site", "kind", "ts", **ctx} — tests
        #: and the bench lane read it to assert the planned faults
        #: actually landed
        self.events: list[dict] = []

    @classmethod
    def parse(cls, spec: str, **kwargs) -> "FaultPlan":
        """Parse ``"site:kind[:key=val,...][;site:kind...]"``.

        Example: ``"worker.batch:kill:after=2,match=mortal;``
        ``protocol.request:drop:max_fires=1"``. Numeric values are
        int/float-coerced; ``max_fires=none`` lifts the one-shot default.
        """
        rules = []
        for part in str(spec).split(";"):
            part = part.strip()
            if not part:
                continue
            bits = part.split(":", 2)
            if len(bits) < 2:
                raise ValueError(
                    f"fault spec {part!r}: want site:kind[:k=v,...]"
                )
            site, kind = bits[0].strip(), bits[1].strip()
            opts: dict = {}
            if len(bits) == 3 and bits[2].strip():
                for kv in bits[2].split(","):
                    k, _, v = kv.partition("=")
                    k, v = k.strip(), v.strip()
                    if k in ("after", "every"):
                        opts[k] = int(v)
                    elif k == "max_fires":
                        opts[k] = None if v.lower() == "none" else int(v)
                    elif k in ("p", "delay_s"):
                        opts[k] = float(v)
                    elif k in ("match", "devices"):
                        opts[k] = v
                    else:
                        raise ValueError(f"unknown fault option {k!r}")
            rules.append(FaultRule(site=site, kind=kind, **opts))
        if not rules:
            raise ValueError(f"empty fault spec {spec!r}")
        return cls(rules, **kwargs)

    @staticmethod
    def _kind_class(kind: str) -> str:
        if kind in _KIND_CORRUPT:
            return "corrupt"
        if kind in _KIND_DEVICE:
            return "device"
        return "raise"

    def _fire_locked(self, site: str, kind_class: str,
                     ctx: dict) -> FaultRule | None:
        """Evaluate the matching rules for one probe/poll; rule counters
        only advance for rules of the REQUESTED class (raise/sleep vs
        corruption vs mesh topology), so mixed plans stay deterministic
        per site."""
        with self._lock:
            for rule in self.rules:
                if rule.site != site:
                    continue
                if self._kind_class(rule.kind) != kind_class:
                    continue
                if rule.match and rule.match not in str(
                        ctx.get("worker_id", "")) \
                        and rule.match not in current_fault_scope():
                    continue
                rule.n_probes += 1
                if rule.n_probes <= rule.after:
                    continue
                if (rule.n_probes - rule.after - 1) % rule.every != 0:
                    continue
                if rule.max_fires is not None \
                        and rule.n_fires >= rule.max_fires:
                    continue
                if rule.p < 1.0 and self._rng.random() >= rule.p:
                    continue
                rule.n_fires += 1
                self.events.append({
                    "site": site, "kind": rule.kind,
                    "ts": self.clock.now(), **ctx,
                })
                return rule  # one fault per probe
        return None

    def probe(self, site: str, **ctx) -> None:
        """Evaluate every raise/sleep rule for ``site``; raise/sleep if
        one fires (corruption rules are polled, not probed)."""
        fired = self._fire_locked(site, "raise", ctx)
        if fired is None:
            return
        self._metrics.counter(
            FAULTS_INJECTED_TOTAL,
            "faults fired by the active FaultPlan",
        ).inc()
        if fired.kind in _KIND_SLEEP:
            self._sleep(fired.delay_s if fired.delay_s is not None
                        else _KIND_SLEEP[fired.kind])
            return
        raise _KIND_EXC[fired.kind](fired.kind, site, **ctx)

    def poll(self, site: str, **ctx) -> str | None:
        """Evaluate the CORRUPTION rules for ``site``; returns the fired
        kind (the caller applies the corruption) or None."""
        fired = self._fire_locked(site, "corrupt", ctx)
        if fired is None:
            return None
        self._metrics.counter(
            FAULTS_INJECTED_TOTAL,
            "faults fired by the active FaultPlan",
        ).inc()
        return fired.kind

    def poll_device(self, site: str, **ctx) -> dict | None:
        """Evaluate the MESH-TOPOLOGY rules for ``site``; returns
        ``{"kind", "devices"}`` (the scheduler applies the loss or
        cordon) or None."""
        fired = self._fire_locked(site, "device", ctx)
        if fired is None:
            return None
        self._metrics.counter(
            FAULTS_INJECTED_TOTAL,
            "faults fired by the active FaultPlan",
        ).inc()
        return {"kind": fired.kind, "devices": fired.device_indices()}

    def n_fired(self, site: str | None = None) -> int:
        with self._lock:
            return sum(r.n_fires for r in self.rules
                       if site is None or r.site == site)


#: the process-global active plan; ``maybe_fault`` reads it lock-free
#: (assignment is atomic) so un-faulted production pays one global read
_ACTIVE: FaultPlan | None = None


def install_fault_plan(plan: FaultPlan) -> FaultPlan:
    global _ACTIVE
    _ACTIVE = plan
    return plan


def uninstall_fault_plan() -> None:
    global _ACTIVE
    _ACTIVE = None


def active_fault_plan() -> FaultPlan | None:
    return _ACTIVE


def maybe_fault(site: str, **ctx) -> None:
    """Probe the active plan, if any (the instrumented sites call this)."""
    plan = _ACTIVE
    if plan is not None:
        plan.probe(site, **ctx)


def maybe_corrupt(site: str, **ctx) -> str | None:
    """Poll the active plan for a numeric-corruption kind at ``site``;
    the caller applies the returned corruption (None = stay clean)."""
    plan = _ACTIVE
    if plan is None:
        return None
    return plan.poll(site, **ctx)


def maybe_device_fault(site: str = "device.mesh", **ctx) -> dict | None:
    """Poll the active plan for a mesh-topology event at ``site``; the
    serving scheduler applies the returned ``{"kind", "devices"}``
    (``device_lost`` reaps + shrinks, ``device_degraded`` cordons).
    None = topology unchanged."""
    plan = _ACTIVE
    if plan is None:
        return None
    return plan.poll_device(site, **ctx)
