"""RetryPolicy — the one backoff schedule every transient-failure site uses.

Before this subsystem each site hand-rolled its own retry behavior:
``protocol.request`` gave up after one connect attempt, the elastic
worker's reconnect loop slept a fixed ``min(poll_s * 4, 2.0)``, and the
async History writer latched sticky-dead on the FIRST persist failure.
One policy object replaces all three: capped exponential backoff with
seeded jitter, deadlines computed on the injected observability clock
(never a raw wall-clock read — the repo lint enforces it), and an
``on_retry`` hook so each site can count its retries into the metrics
registry.

Determinism: jitter draws from a caller-seeded ``random.Random``, so a
test (or a FaultPlan-driven CI lane) replays the exact same backoff
sequence every run.
"""
from __future__ import annotations

import random
import time
from dataclasses import dataclass

from ..observability import SYSTEM_CLOCK


@dataclass(frozen=True)
class RetryPolicy:
    """Capped exponential backoff with jitter.

    ``attempts``: total tries (1 = no retry). ``base_s`` doubles (times
    ``multiplier``) per retry up to ``max_s``; ``jitter`` is the fraction
    of each delay randomized uniformly in ``[1 - jitter, 1 + jitter]``
    (full delays synchronize retry storms across a worker pool — the
    classic thundering-herd failure of jitter-free backoff).
    """

    attempts: int = 3
    base_s: float = 0.05
    max_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.5

    def __post_init__(self):
        if self.attempts < 1:
            raise ValueError(f"attempts must be >= 1, got {self.attempts}")

    def delay_s(self, retry_index: int, rng: random.Random | None = None
                ) -> float:
        """Backoff before retry ``retry_index`` (0-based)."""
        d = min(self.base_s * (self.multiplier ** retry_index), self.max_s)
        if self.jitter > 0 and rng is not None:
            d *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return max(d, 0.0)

    def delays(self, rng: random.Random | None = None):
        """The ``attempts - 1`` backoff delays, in order."""
        return [self.delay_s(i, rng) for i in range(self.attempts - 1)]

    def call(self, fn, *, retry_on=(ConnectionError, OSError),
             rng: random.Random | None = None,
             sleep=time.sleep, clock=None, deadline_s: float | None = None,
             on_retry=None):
        """Run ``fn()`` under this policy.

        Retries on ``retry_on`` exceptions only; everything else
        propagates immediately (a genuine bug must not be retried into
        an n-times-repeated bug). ``deadline_s`` bounds the TOTAL time
        across attempts on the injected ``clock`` (default: the shared
        SYSTEM_CLOCK); ``on_retry(retry_index, exc)`` fires before each
        backoff sleep. The last failure re-raises unchanged.
        """
        clock = clock if clock is not None else SYSTEM_CLOCK
        t_end = (clock.now() + float(deadline_s)
                 if deadline_s is not None else None)
        for i in range(self.attempts):
            try:
                return fn()
            except retry_on as exc:
                if i >= self.attempts - 1:
                    raise
                if t_end is not None and clock.now() >= t_end:
                    raise
                if on_retry is not None:
                    on_retry(i, exc)
                sleep(self.delay_s(i, rng))


#: the shared default: 3 tries with 50 ms -> 100 ms backoff. Sized for a
#: broker blip (process restart, transient accept-queue overflow), not a
#: broker death — callers with their own liveness loop (the worker's
#: hello poll) layer reconnect backoff on top.
DEFAULT_RETRY_POLICY = RetryPolicy(attempts=3, base_s=0.05, max_s=0.5)

#: persist-side default: sqlite "database is locked" contention clears in
#: tens of milliseconds; three spaced tries before the writer latches
#: sticky keeps the sticky semantics for genuinely broken db state
DEFAULT_PERSIST_RETRY_POLICY = RetryPolicy(attempts=3, base_s=0.05,
                                           max_s=1.0)
