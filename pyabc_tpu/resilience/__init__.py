"""pyabc_tpu.resilience — fault injection, self-healing, checkpoint/restore.

The fault-tolerance subsystem (round 9). Three coordinated layers keep
an ABC-SMC run alive through the failures the elastic path merely
OBSERVED before (PRs 1-4 built the observability to see dark time; this
package acts on it):

- :mod:`.faults` — deterministic fault injection: a seeded,
  clock-injected :class:`FaultPlan` kills/hangs/slows workers mid-batch,
  drops broker connections, fails History persists and simulates device
  resets — from tests, ``abc-worker --fault-plan``, and the bench
  ``resilience`` lane, so self-healing is proven on every CPU CI run.
- :mod:`.retry` + :mod:`.lease` — self-healing elastic sampling: one
  shared :class:`RetryPolicy` behind ``protocol.request`` and the
  worker reconnect loop; broker batch handouts become LEASES
  (:class:`LeaseTable`) with deadlines on the injected clock, expired /
  presumed-dead work requeues to live workers, and slot-level dedup
  drops late duplicates exactly-once.
- :mod:`.checkpoint` — mid-chunk device checkpointing: the fused
  multigen loop's carry (RNG key data, fitted-proposal state, epsilon /
  pdf-norm trail, refit cadence counter) round-trips bit-exact through
  :class:`CheckpointManager` with atomic rename + a CRC32/schema-version
  header (a corrupt or truncated file raises a typed
  :class:`CheckpointCorruptError` and resume falls back to History
  replay), so a killed orchestrator resumes mid-chunk instead of
  replaying from the last History generation.
- :mod:`.health` — numerical/statistical health supervision (round 10):
  the host half of the in-kernel per-generation health word
  (:mod:`pyabc_tpu.ops.health`) — :class:`RunSupervisor` maps NaN/Inf,
  ESS collapse, PSD failure and epsilon stalls to budgeted recovery
  actions (rollback / forced refit / proposal widening) or a typed
  :class:`DegenerateRunError` carrying the per-generation health trail.

Every recovery action emits spans/metrics through the PR 1 observability
spine (``pyabc_tpu_faults_injected_total``,
``pyabc_tpu_batches_redispatched_total``, ``recovery.*`` spans feed
``elastic_gap_attribution``); all deadlines live on the injected clock
(enforced by ``tests/test_observability_lint.py``).
"""
from .checkpoint import (
    CHECKPOINT_VERSION,
    CheckpointCorruptError,
    CheckpointManager,
    decode_tree,
    encode_tree,
    tree_bit_equal,
)
from .faults import (
    FaultPlan,
    FaultRule,
    InjectedConnectionError,
    InjectedDeviceReset,
    InjectedFault,
    InjectedKill,
    InjectedPersistError,
    InjectedTransientError,
    active_fault_plan,
    current_fault_scope,
    fault_scope,
    install_fault_plan,
    maybe_corrupt,
    maybe_device_fault,
    maybe_fault,
    uninstall_fault_plan,
)
from .health import DegenerateRunError, RunSupervisor, decode_health
from .lease import LeaseTable
from .retry import (
    DEFAULT_PERSIST_RETRY_POLICY,
    DEFAULT_RETRY_POLICY,
    RetryPolicy,
)

__all__ = [
    "CHECKPOINT_VERSION", "CheckpointCorruptError", "CheckpointManager",
    "decode_tree", "encode_tree", "tree_bit_equal",
    "FaultPlan", "FaultRule", "InjectedFault", "InjectedKill",
    "InjectedConnectionError", "InjectedTransientError",
    "InjectedPersistError", "InjectedDeviceReset",
    "active_fault_plan", "install_fault_plan", "maybe_fault",
    "maybe_corrupt", "maybe_device_fault", "uninstall_fault_plan",
    "fault_scope", "current_fault_scope",
    "DegenerateRunError", "RunSupervisor", "decode_health",
    "LeaseTable",
    "RetryPolicy", "DEFAULT_RETRY_POLICY", "DEFAULT_PERSIST_RETRY_POLICY",
]
