"""Batch leases: handed-out work the broker can take back.

Pre-round-9, a broker slot range was fire-and-forget: a worker that died
with slots in flight simply never delivered them, which was harmless in
pure dynamic mode (completion is acceptance-driven) but STALLED
``wait_for_all`` and static-quota generations until the sampler's
``generation_timeout`` — "bounded by the timeout, not self-healing"
(broker.py's own docstring). This module turns every handout into a
LEASE: ``(worker, slot range, deadline)`` on the injected clock. Expired
or presumed-dead leases requeue their undelivered slots, the next
``get_slots`` from a live worker redispatches them, and slot-level dedup
drops a late duplicate delivery exactly-once (so a slow-but-alive worker
whose lease was reassigned cannot double-count a batch — which also
makes retried ``results`` messages safe when the first attempt's reply
was lost on the wire).

Dedup semantics per scheduling mode: dynamic slots yield exactly one
result each, so ANY second delivery of a slot is dropped; static quota
units legitimately ship many reject records under one slot id, so only
a second ACCEPTED delivery per slot is dropped (acceptance is the unit
of accounting there).

Pure bookkeeping on one injected clock — no threads, no sockets; the
owning :class:`~pyabc_tpu.broker.broker.EvalBroker` calls it under its
own lock.
"""
from __future__ import annotations

from collections import deque


class LeaseTable:
    """Outstanding slot leases + requeue + dedup for ONE generation.

    Cumulative counters (``redispatched_total``, ``duplicates_dropped``,
    ``leases_expired``) survive :meth:`reset` — they are run-lifetime
    observability, not generation state.
    """

    def __init__(self, clock, timeout_s: float = 30.0):
        self.clock = clock
        self.timeout_s = float(timeout_s)
        #: lease_id -> {"wid", "slots": set, "deadline", "start", "stop"}
        self._leases: dict[int, dict] = {}
        self._next_id = 0
        #: slot -> lease_id for every undelivered outstanding slot
        self._slot_owner: dict[int, int] = {}
        #: requeued (slot_start, slot_stop, requeued_at) ranges, FIFO
        self._requeue: deque = deque()
        #: slots delivered at least once (dynamic dedup)
        self._delivered: set[int] = set()
        #: slots with an ACCEPTED delivery (static dedup)
        self._accepted: set[int] = set()
        # run-lifetime counters
        self.redispatched_total = 0
        self.duplicates_dropped = 0
        self.leases_expired = 0

    # ----------------------------------------------------------- lifecycle
    def reset(self) -> None:
        """New generation: drop per-generation state, keep counters."""
        self._leases.clear()
        self._slot_owner.clear()
        self._requeue.clear()
        self._delivered.clear()
        self._accepted.clear()

    # ------------------------------------------------------------- granting
    def grant(self, wid: str, start: int, stop: int) -> int:
        """Lease ``[start, stop)`` to ``wid``; returns the lease id."""
        lease_id = self._next_id
        self._next_id += 1
        slots = set(range(int(start), int(stop)))
        self._leases[lease_id] = {
            "wid": str(wid), "slots": slots,
            "deadline": self.clock.now() + self.timeout_s,
            "start": int(start), "stop": int(stop),
        }
        for s in slots:
            self._slot_owner[s] = lease_id
        return lease_id

    def take_requeued(self, wid: str, k: int) -> tuple[int, int, float] | None:
        """Redispatch up to ``k`` requeued slots to ``wid``.

        Returns ``(start, stop, orphaned_at)`` — the range to hand out
        (re-leased to ``wid``) and the instant the work was orphaned
        (its dead owner's last contact; the broker turns the
        orphaned->redispatched interval into a ``recovery.redispatch``
        span). None when nothing is queued.
        """
        if not self._requeue:
            return None
        start, stop, ts = self._requeue.popleft()
        k = max(int(k), 1)
        if stop - start > k:
            # split: serve the head, requeue the tail (same orphan time)
            self._requeue.appendleft((start + k, stop, ts))
            stop = start + k
        self.grant(wid, start, stop)
        self.redispatched_total += 1
        return (start, stop, ts)

    # ------------------------------------------------------------ delivery
    def touch_worker(self, wid: str) -> None:
        """Any contact from ``wid`` extends its leases: a slow-but-alive
        worker mid-long-batch must not lose its work to the timeout."""
        deadline = self.clock.now() + self.timeout_s
        for lease in self._leases.values():
            if lease["wid"] == wid:
                lease["deadline"] = deadline

    def admit(self, slot: int, accepted: bool, mode: str) -> bool:
        """Should this delivered triple be counted? (exactly-once dedup)"""
        slot = int(slot)
        if mode == "static":
            # quota units ship every reject + one accept per slot; only
            # the ACCEPT is unit-of-account and must land exactly once
            if accepted:
                if slot in self._accepted:
                    self.duplicates_dropped += 1
                    return False
                self._accepted.add(slot)
            return True
        if slot in self._delivered:
            self.duplicates_dropped += 1
            return False
        self._delivered.add(slot)
        return True

    def note_delivery(self, slot: int) -> None:
        """Mark ``slot`` delivered: release it from its owning lease."""
        lease_id = self._slot_owner.pop(int(slot), None)
        if lease_id is None:
            return
        lease = self._leases.get(lease_id)
        if lease is None:
            return
        lease["slots"].discard(int(slot))
        if not lease["slots"]:
            del self._leases[lease_id]

    # -------------------------------------------------------------- reaping
    def reap(self, now: float, dead_wids=()) -> list[dict]:
        """Requeue every lease that expired or belongs to a dead worker.

        Returns one event per reaped lease:
        ``{"wid", "n_slots", "ranges", "reason"}``.
        """
        dead = set(dead_wids)
        events = []
        for lease_id in list(self._leases):
            lease = self._leases[lease_id]
            expired = now > lease["deadline"]
            is_dead = lease["wid"] in dead
            if not (expired or is_dead):
                continue
            ranges = _runs(sorted(lease["slots"]))
            # the work was ORPHANED at the owner's last contact (deadline
            # minus the timeout = the last grant/touch instant), not at
            # reap time — the recovery span must cover the whole stall
            # the dead worker caused, or gap attribution under-reports
            # recovery time by exactly the detection latency
            orphaned_at = min(lease["deadline"] - self.timeout_s, now)
            for a, b in ranges:
                self._requeue.append((a, b, orphaned_at))
            for s in lease["slots"]:
                self._slot_owner.pop(s, None)
            del self._leases[lease_id]
            self.leases_expired += 1
            events.append({
                "wid": lease["wid"],
                "n_slots": sum(b - a for a, b in ranges),
                "ranges": ranges,
                "reason": "presumed_dead" if is_dead else "lease_expired",
            })
        return events

    def reap_wids(self, wids, reason: str = "owner_reaped") -> list[dict]:
        """Immediately reap EVERY lease belonging to ``wids``, deadline
        notwithstanding — the host-loss path (round 18 fleets): when a
        machine dies, all of its owners' leases go at once; waiting out
        the per-lease timeout would stall requeue by exactly the
        detection latency. Returns the same events as :meth:`reap`,
        tagged ``reason``."""
        dead = {str(w) for w in wids}
        now = self.clock.now()
        events = []
        for lease_id in list(self._leases):
            lease = self._leases[lease_id]
            if lease["wid"] not in dead:
                continue
            ranges = _runs(sorted(lease["slots"]))
            orphaned_at = min(lease["deadline"] - self.timeout_s, now)
            for a, b in ranges:
                self._requeue.append((a, b, orphaned_at))
            for s in lease["slots"]:
                self._slot_owner.pop(s, None)
            del self._leases[lease_id]
            self.leases_expired += 1
            events.append({
                "wid": lease["wid"],
                "n_slots": sum(b - a for a, b in ranges),
                "ranges": ranges,
                "reason": str(reason),
            })
        return events

    def discard_requeued(self) -> int:
        """Drop every requeued range without redispatching it; returns
        the number of slots discarded.

        For BATCH leases the requeue deque feeds ``take_requeued`` — a
        successor worker picks the orphaned slots up. RUN-level leases
        (the serving scheduler: one lease = one tenant's whole slot)
        reclaim and requeue the TENANT instead, so nothing ever pops
        the deque; the owner must discard after reaping or the ranges
        accumulate for the process lifetime."""
        n = sum(b - a for a, b, _t in self._requeue)
        self._requeue.clear()
        return n

    # ---------------------------------------------------------------- views
    def stats(self) -> dict:
        return {
            "outstanding_leases": len(self._leases),
            "outstanding_slots": len(self._slot_owner),
            "requeued_slots": sum(b - a for a, b, _t in self._requeue),
            "redispatched_total": self.redispatched_total,
            "duplicates_dropped": self.duplicates_dropped,
            "leases_expired": self.leases_expired,
            "timeout_s": self.timeout_s,
        }


def _runs(sorted_slots) -> list[tuple[int, int]]:
    """Contiguous [start, stop) runs of a sorted slot list — requeued
    work still travels the wire in the ("slots", start, stop) shape."""
    runs: list[tuple[int, int]] = []
    for s in sorted_slots:
        if runs and s == runs[-1][1]:
            runs[-1] = (runs[-1][0], s + 1)
        else:
            runs.append((s, s + 1))
    return runs
