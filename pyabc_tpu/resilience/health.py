"""Host-side numerical/statistical health supervision (ISSUE 6 tentpole).

The device half (:mod:`pyabc_tpu.ops.health`) computes a per-generation
health word inside the fused multigen kernel and ships it on the packed
fetch; this module is the host half: decode the word, keep the
per-generation health TRAIL, and map detected conditions to recovery
actions the fused loop executes:

==================== =======================================================
condition            action
==================== =======================================================
NaN/Inf in theta /   ``rollback`` — abort the chunk (nothing at or past the
weights / distances, failed generation is persisted), roll the carry back to
non-finite epsilon,  the PR 5 checkpoint / the last healthy chunk-boundary
zero total weight    carry, and redispatch; with deterministic one-shot
                     corruption the recovered trajectory is BIT-identical
PSD / Cholesky       ``refit`` — rebuild the carry from a forced fresh HOST
failure (survived    transition fit on the last healthy population (the
the jitter ladder)   in-kernel factors are not trusted)
ESS below the floor, ``widen`` — same host rebuild, with the proposal
acceptance collapse  bandwidth inflated by ``widen_factor`` (weights are
                     always computed against the proposal actually sampled
                     from, so widening is statistically exact)
epsilon stall        ``terminate`` — the run is burning device time without
                     progress: graceful :class:`DegenerateRunError`
==================== =======================================================

Recovery is BUDGETED: more than ``max_rollbacks`` recovery attempts per
run means the degeneracy is persistent (e.g. a re-poisoned carry, a
model that genuinely cannot reach the floor) and the run terminates with
a typed :class:`DegenerateRunError` carrying the full health trail —
"silently wrong" becomes "loudly diagnosed".

Every decision lands on the observability spine: a ``health.<action>``
span on the ``health`` pseudo-thread (clipped to the detection->redispatch
window, so gap attribution sees recovery time) and
``pyabc_tpu_health_events_total`` counters per condition kind.
"""
from __future__ import annotations

from ..observability import NULL_METRICS, NULL_TRACER, SYSTEM_CLOCK
from ..observability.metrics import (
    CHUNK_ROLLBACKS_TOTAL,
    DEGENERATE_RUNS_TOTAL,
    HEALTH_EVENTS_TOTAL,
    health_event_metric,
)
from ..ops.health import (  # noqa: F401  (re-exported host vocabulary)
    BIT_ACC_COLLAPSE,
    BIT_EPS_NONFINITE,
    BIT_EPS_STALL,
    BIT_ESS_FLOOR,
    BIT_NAMES,
    BIT_NAN_DISTANCE,
    BIT_NAN_THETA,
    BIT_NAN_WEIGHT,
    BIT_PSD_FAIL,
    BIT_WEIGHT_ZERO,
    POISON_KINDS,
)

#: bits whose only sound recovery is rolling the carry back to a known-
#: good state: the population itself is numerically corrupt
_ROLLBACK_BITS = (BIT_NAN_THETA | BIT_NAN_WEIGHT | BIT_NAN_DISTANCE
                  | BIT_WEIGHT_ZERO | BIT_EPS_NONFINITE)
#: statistical (not numerical) degeneracy: the proposal is too narrow /
#: mis-centred — widen its bandwidth on the rebuild
_WIDEN_BITS = BIT_ESS_FLOOR | BIT_ACC_COLLAPSE


def decode_health(word: int) -> list[str]:
    """Kind names set in a health word, in bit order."""
    word = int(word)
    return [name for i, name in enumerate(BIT_NAMES) if word & (1 << i)]


class DegenerateRunError(RuntimeError):
    """A run terminated by the health supervisor.

    ``trail`` is the per-generation health record list (every nonzero
    health word observed, with the action taken) — the diagnosis ships
    WITH the failure instead of being reconstructed from logs.
    """

    def __init__(self, message: str, trail: list[dict]):
        super().__init__(message)
        self.trail = list(trail)


class RunSupervisor:
    """Maps in-kernel health words to recovery actions, under a budget.

    One instance per run (the trail and the rollback budget are run
    state). The fused loop calls :meth:`on_failure` with each first
    nonzero health word of a fetched chunk; the supervisor records the
    event, emits metrics/spans, and returns the action — or raises
    :class:`DegenerateRunError` when the condition is terminal or the
    recovery budget is exhausted.
    """

    def __init__(self, *, max_rollbacks: int = 2, widen_factor: float = 1.5,
                 clock=None, tracer=None, metrics=None):
        self.max_rollbacks = int(max_rollbacks)
        self.widen_factor = float(widen_factor)
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.metrics = metrics if metrics is not None else NULL_METRICS
        #: every nonzero health word observed, in detection order
        self.trail: list[dict] = []
        self.rollbacks = 0

    @staticmethod
    def action_for(word: int) -> str:
        """The recovery action for a health word (precedence: a stall is
        terminal; numerical corruption outranks a bad factorization
        outranks statistical degeneracy)."""
        word = int(word)
        if word & BIT_EPS_STALL:
            return "terminate"
        if word & _ROLLBACK_BITS:
            return "rollback"
        if word & BIT_PSD_FAIL:
            return "refit"
        if word & _WIDEN_BITS:
            return "widen"
        return "rollback"  # unknown future bits: the conservative action

    def on_failure(self, t: int, word: int, **info) -> str:
        """Record one detected failure and decide; raises
        :class:`DegenerateRunError` for terminal conditions."""
        kinds = decode_health(word)
        action = self.action_for(word)
        entry = {"t": int(t), "word": int(word), "kinds": kinds,
                 "action": action, "ts": self.clock.now(),
                 **{k: v for k, v in info.items() if v is not None}}
        self.trail.append(entry)
        self.metrics.counter(
            HEALTH_EVENTS_TOTAL,
            "nonzero in-kernel health words acted on",
        ).inc()
        for kind in kinds:
            self.metrics.counter(
                health_event_metric(kind),
                f"health events of kind {kind}",
            ).inc()
        if action == "terminate":
            self._degenerate(
                f"epsilon progress stalled at t={t} "
                f"(kinds={kinds}): terminating a run that is burning "
                f"device time without converging"
            )
        if self.rollbacks >= self.max_rollbacks:
            self._degenerate(
                f"health recovery budget exhausted "
                f"({self.rollbacks}/{self.max_rollbacks} rollbacks) at "
                f"t={t} (kinds={kinds}): the degeneracy is persistent"
            )
        self.rollbacks += 1
        self.metrics.counter(
            CHUNK_ROLLBACKS_TOTAL,
            "fused chunks aborted and rolled back by the health "
            "supervisor",
        ).inc()
        return action

    def note_recovered(self, t: int, action: str, source: str,
                       t_detect: float) -> None:
        """Close the recovery window: one ``health.<action>`` span on the
        ``health`` pseudo-thread covering detection -> redispatch, so
        coverage/gap accounting attributes recovery time instead of
        reporting it dark."""
        now = self.clock.now()
        if self.trail:
            self.trail[-1]["recovery_source"] = source
            self.trail[-1]["recovery_s"] = round(now - t_detect, 6)
        self.tracer.record_span(
            f"health.{action}", t_detect, now, thread="health",
            t=int(t), source=source,
        )

    def _degenerate(self, message: str) -> None:
        self.metrics.counter(
            DEGENERATE_RUNS_TOTAL,
            "runs terminated with DegenerateRunError",
        ).inc()
        if self.trail:
            self.trail[-1]["action"] = "terminate"
        raise DegenerateRunError(message, self.trail)
