"""Global matplotlib styling helper.

Reference parity: ``pyabc/settings.py::set_figure_params`` — the one
global configuration hook the reference exposes (everything else is
constructor kwargs). Styles matplotlib for the visualization module;
a no-op import-wise when matplotlib is absent until actually called.
"""
from __future__ import annotations


def set_figure_params(theme: str = "pyabc", style: str | None = None,
                      color_map: str = "viridis") -> None:
    """Apply a plotting theme to matplotlib rcParams.

    ``theme='pyabc'`` mirrors the reference's look (clean spines, colormap
    default); ``theme='default'`` restores matplotlib defaults. ``style``
    forwards to ``matplotlib.style.use`` when given.
    """
    import matplotlib as mpl

    if theme == "default":
        mpl.rcdefaults()
        return
    if theme != "pyabc":
        raise ValueError(f"unknown theme: {theme!r} (use 'pyabc'/'default')")
    if style is not None:
        import matplotlib.style

        matplotlib.style.use(style)
    mpl.rcParams.update({
        "image.cmap": color_map,
        "axes.spines.top": False,
        "axes.spines.right": False,
        "axes.grid": True,
        "grid.alpha": 0.3,
        "figure.autolayout": True,
    })
