from .aggregate import (
    AdaptiveAggregatedDistance,
    AggregatedDistance,
    DistanceWithMeasureList,
    MinMaxDistance,
    PCADistance,
    PercentileDistance,
    RangeEstimatorDistance,
    ZScoreDistance,
)
from .base import (
    AcceptAllDistance,
    Distance,
    IdentityFakeDistance,
    NoDistance,
    SimpleFunctionDistance,
    to_distance,
)
from .kernel import (
    SCALE_LIN,
    SCALE_LOG,
    BinomialKernel,
    FunctionKernel,
    IndependentLaplaceKernel,
    IndependentNormalKernel,
    NegativeBinomialKernel,
    NormalKernel,
    PoissonKernel,
    StochasticKernel,
)
from .pnorm import AdaptivePNormDistance, PNormDistance
from . import scale

__all__ = [
    "Distance", "NoDistance", "IdentityFakeDistance", "AcceptAllDistance",
    "SimpleFunctionDistance", "to_distance",
    "PNormDistance", "AdaptivePNormDistance",
    "AggregatedDistance", "AdaptiveAggregatedDistance",
    "DistanceWithMeasureList", "ZScoreDistance", "PCADistance",
    "MinMaxDistance", "PercentileDistance", "RangeEstimatorDistance",
    "StochasticKernel", "NormalKernel", "IndependentNormalKernel",
    "IndependentLaplaceKernel", "BinomialKernel", "PoissonKernel",
    "NegativeBinomialKernel", "FunctionKernel", "SCALE_LIN", "SCALE_LOG",
    "scale",
]
