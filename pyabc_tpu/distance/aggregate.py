"""Aggregated / compound distances.

Reference parity: ``pyabc/distance/distance.py::{AggregatedDistance,
AdaptiveAggregatedDistance, DistanceWithMeasureList, ZScoreDistance,
PCADistance, MinMaxDistance, PercentileDistance, RangeEstimatorDistance}``.
"""
from __future__ import annotations

from typing import Callable, Sequence

import jax.numpy as jnp
import numpy as np

from ..core.sumstat_spec import SumStatSpec
from .base import Distance, to_distance
from .pnorm import _as_flat


class AggregatedDistance(Distance):
    """Weighted sum of sub-distances (pyabc AggregatedDistance).

    d(x, x0) = sum_k factor_k * w_k * d_k(x, x0); weights may vary per
    generation (dict {t: vector}).
    """

    def __init__(self, distances: Sequence, weights=None, factors=None):
        self.distances = [to_distance(d) for d in distances]
        if weights is None:
            self.weights = {-1: np.ones(len(self.distances))}
        elif isinstance(weights, dict):
            self.weights = {
                int(t): np.asarray(w, np.float64) for t, w in weights.items()
            }
        else:
            self.weights = {-1: np.asarray(weights, np.float64)}
        self.factors = (
            np.ones(len(self.distances))
            if factors is None
            else np.asarray(factors, np.float64)
        )

    def initialize(self, t, get_all_sum_stats=None, x_0=None):
        for d in self.distances:
            d.initialize(t, get_all_sum_stats, x_0)

    def configure_sampler(self, sampler):
        for d in self.distances:
            d.configure_sampler(sampler)

    def update(self, t, get_all_sum_stats=None) -> bool:
        return any([d.update(t, get_all_sum_stats) for d in self.distances])

    def _weights_for(self, t):
        if t is not None:
            past = [s for s in self.weights if 0 <= s <= t]
            if past:
                return self.weights[max(past)]
        return self.weights.get(-1, np.ones(len(self.distances)))

    def __call__(self, x, x_0, t=None, par=None) -> float:
        vals = np.asarray([d(x, x_0, t, par) for d in self.distances])
        return float(np.sum(self._weights_for(t) * self.factors * vals))

    def is_device_compatible(self) -> bool:
        return all(d.is_device_compatible() for d in self.distances)

    def device_params(self, t=None):
        return (
            jnp.asarray(self._weights_for(t) * self.factors, jnp.float32),
            tuple(d.device_params(t) for d in self.distances),
        )

    def device_fn(self, spec: SumStatSpec):
        fns = [d.device_fn(spec) for d in self.distances]

        def fn(x, x0, params):
            w, subparams = params
            vals = jnp.stack(
                [f(x, x0, p) for f, p in zip(fns, subparams)]
            )
            return jnp.sum(w * vals)

        return fn

    def device_bound_fn(self, spec: SumStatSpec):
        """Monotone prefix bound for the weighted sum of plain p-norm
        sub-distances: each sub accumulates its own p-th-power partial
        sum (running max at p=inf), and the combined bound
        ``sum_k w_k * acc_k^(1/p_k)`` is non-decreasing as entries fold
        in whenever every top-level weight is non-negative — which the
        early-reject capability gate checks host-side. Non-p-norm or
        transformed subs have no sound per-prefix bound (None)."""
        from .pnorm import PNormDistance

        if not all(type(d) is PNormDistance and d.sumstat is None
                   for d in self.distances):
            return None
        ps = [d.p for d in self.distances]
        rtol = PNormDistance.BOUND_RTOL
        n_sub = len(self.distances)

        def init():
            return jnp.zeros((n_sub,), jnp.float32)

        def step(acc, vals, idx, x0, params):
            _w_top, subparams = params
            parts = []
            for k, p in enumerate(ps):
                diff = subparams[k][idx] * jnp.abs(vals - x0[idx])
                if np.isinf(p):
                    parts.append(jnp.maximum(acc[k], jnp.max(diff)))
                else:
                    parts.append(acc[k] + jnp.sum(diff ** p))
            return jnp.stack(parts)

        def exceeds(acc, threshold, params):
            w_top, _subparams = params
            vals = []
            for k, p in enumerate(ps):
                vals.append(acc[k] if np.isinf(p)
                            else acc[k] ** (1.0 / p))
            total = jnp.sum(w_top * jnp.stack(vals))
            return total > threshold * (1.0 + rtol)

        return {"init": init, "step": step, "exceeds": exceeds}


class AdaptiveAggregatedDistance(AggregatedDistance):
    """Aggregated distance that rescales sub-distances each generation so all
    contribute comparably (pyabc AdaptiveAggregatedDistance). The scale of a
    sub-distance is estimated over all recorded simulations by evaluating it
    against the observation."""

    def __init__(self, distances: Sequence,
                 scale_function: Callable | None = None,
                 adaptive: bool = True, log_file: str | None = None):
        super().__init__(distances)
        self.scale_function = scale_function or _span_of_values
        self.adaptive = adaptive
        self.log_file = log_file
        self._x_0 = None

    def requires_calibration(self) -> bool:
        return True

    def configure_sampler(self, sampler):
        super().configure_sampler(sampler)
        if self.adaptive:
            sampler.sample_factory.record_rejected = True

    def initialize(self, t, get_all_sum_stats=None, x_0=None):
        super().initialize(t, get_all_sum_stats, x_0)
        self._x_0 = x_0
        if get_all_sum_stats is not None:
            self._fit(t, get_all_sum_stats)

    def update(self, t, get_all_sum_stats=None) -> bool:
        changed = super().update(t, get_all_sum_stats)
        if not self.adaptive or get_all_sum_stats is None:
            return changed
        self._fit(t, get_all_sum_stats)
        return True

    def _fit(self, t, get_all_sum_stats):
        from ..sampler.base import DeviceRecords

        samples = get_all_sum_stats()
        scales = None
        if isinstance(samples, DeviceRecords) and samples.scale is not None \
                and self.device_scale_impl() is not None:
            # the generation kernel already reduced the on-device record
            # ring to the (K,) scale vector (device_record_reduce) — no
            # ring fetch, no per-record host distance loop
            scales = np.asarray(samples.scale, np.float64)
        if scales is None:
            # per-sub-distance value of each recorded simulation vs the
            # observation, reduced on the host
            vals = np.asarray(
                [
                    [d(self._unflatten(s), self._x_0, t)
                     for d in self.distances]
                    for s in np.asarray(samples)
                ]
            )  # (n, K)
            scales = np.asarray(
                [self.scale_function(vals[:, k])
                 for k in range(vals.shape[1])]
            )
        w = np.zeros_like(scales)
        pos = scales > 0
        w[pos] = 1.0 / scales[pos]
        self.weights[int(t)] = w

    def _unflatten(self, flat):
        for d in self.distances:
            spec = getattr(d, "spec", None)
            if spec is not None:
                return spec.unflatten(np.asarray(flat))
        return np.asarray(flat)

    # ------------------------------------------------------------- device
    #: scale functions whose device twins need the observation argument —
    #: they take (samples, x_0) and cannot be applied to the 1-arg
    #: per-sub-distance value columns this class reduces over (the host
    #: _fit would TypeError on them too)
    _TWO_ARG_SCALES = frozenset({
        "bias", "root_mean_square_deviation",
        "median_absolute_deviation_to_observation",
        "mean_absolute_deviation_to_observation",
        "combined_median_absolute_deviation",
        "combined_mean_absolute_deviation",
        "standard_deviation_to_observation",
    })

    def device_scale_impl(self):
        """Traceable twin of ``scale_function`` applied column-wise over
        the (n_records, K) sub-distance value matrix, or None when only
        the host can run it (custom function, observation-dependent
        scale). Mirrors AdaptivePNormDistance.device_scale_impl."""
        from .scale import SCALE_FUNCTIONS, _device_scale_impls

        if self.scale_function is _span_of_values:
            return _device_scale_impls().get("span")
        name = getattr(self.scale_function, "__name__", "")
        if SCALE_FUNCTIONS.get(name) is not self.scale_function:
            return None  # custom fn shadowing a builtin name: host path
        if name in self._TWO_ARG_SCALES:
            return None
        return _device_scale_impls().get(name)

    def _subs_device_constant(self) -> bool:
        """True when every sub-distance is a plain, generation-constant
        PNormDistance — the only case where the device reduction's
        ``device_params(None)`` is guaranteed to equal the host's
        per-generation sub-distance evaluation."""
        from .pnorm import PNormDistance

        return all(
            type(d) is PNormDistance and d.sumstat is None
            and not any(k >= 0 for k in d.weights)
            for d in self.distances
        )

    def device_record_reduce(self, spec: SumStatSpec | None = None):
        """Per-generation scale reduction traced INTO the multigen kernel:
        evaluate every sub-distance against the observation over the
        on-device record ring, then scale each value column (the traceable
        twin of :meth:`_fit` — reference AdaptiveAggregatedDistance
        semantics, SURVEY.md §2.2 aggregated row)."""
        import jax

        impl = self.device_scale_impl()
        if impl is None or not self.adaptive \
                or not self._subs_device_constant():
            return None
        fns = [d.device_fn(spec) for d in self.distances]
        sub_params = tuple(d.device_params(None) for d in self.distances)
        n_sub = len(fns)

        def reduce(rec_ss, valid, x0):
            vals = jnp.stack(
                [
                    jax.vmap(lambda s, f=f, p=p: f(s, x0, p))(rec_ss)
                    for f, p in zip(fns, sub_params)
                ],
                axis=1,
            )  # (n_records, K)
            return impl(vals, valid, jnp.zeros(n_sub, jnp.float32))

        return reduce

    def _sharded_scale_name(self) -> str | None:
        """Validated builtin scale name applicable to the 1-arg value
        columns this class reduces over, or None (custom function,
        observation-dependent scale — host semantics would differ)."""
        from .scale import SCALE_FUNCTIONS

        if self.scale_function is _span_of_values:
            return "span"
        name = getattr(self.scale_function, "__name__", "")
        if SCALE_FUNCTIONS.get(name) is not self.scale_function:
            return None
        if name in self._TWO_ARG_SCALES:
            return None
        return name

    def sharded_scale_capable(self) -> bool:
        """True when the per-generation sub-distance rescaling is
        expressible over the fixed per-shard moment block of the value
        columns — the condition for the SHARDED multigen kernel."""
        from ..ops.scale_reduce import SHARDED_SCALE_NAMES

        if not self.adaptive or not self._subs_device_constant():
            return False
        name = self._sharded_scale_name()
        return name is not None and name in SHARDED_SCALE_NAMES

    def device_sharded_reduce(self, spec: SumStatSpec | None = None):
        """Moment-expressed scale reduction for the sharded multigen
        kernel: record columns are the per-record sub-distance values vs
        the observation (the same columns :meth:`device_record_reduce`
        scales), with a zero observation column vector as the moment
        center (the value-column scales never reference x0 — enforced by
        the two-arg exclusion)."""
        import jax

        from ..ops.scale_reduce import MOMENT_ROWS

        if not self.sharded_scale_capable():
            return None
        fns = [d.device_fn(spec) for d in self.distances]
        sub_params = tuple(d.device_params(None) for d in self.distances)
        n_sub = len(fns)

        def cols(rec_ss, x0):
            return jnp.stack(
                [
                    jax.vmap(lambda s, f=f, p=p: f(s, x0, p))(rec_ss)
                    for f, p in zip(fns, sub_params)
                ],
                axis=1,
            )  # (n_records, K)

        return {
            "cols": cols, "x0_cols": jnp.zeros(n_sub, jnp.float32),
            "name": self._sharded_scale_name(),
            "moment_rows": MOMENT_ROWS, "cols_dim": n_sub,
        }

    def device_sharded_dfeat(self, spec: SumStatSpec | None = None):
        """In-lane distance features for the SHARDED kernel's
        recompute-under-new-weights step (see
        AdaptivePNormDistance.device_sharded_dfeat): the features are the
        per-row sub-distance values, the combine the weighted sum with
        the refit 1/scale weights (sub-params chunk-constant under the
        non-adaptive-subs gate)."""
        fns = [d.device_fn(spec) for d in self.distances]
        sub_params = tuple(d.device_params(None) for d in self.distances)

        def row(ss, x0):
            return jnp.stack(
                [f(ss, x0, p) for f, p in zip(fns, sub_params)]
            )

        def combine(feat, params):
            wf, _subs = params
            return jnp.sum(wf * feat)

        return {"row": row, "combine": combine, "dim": len(fns)}

    def device_weight_update(self):
        """Traceable scale -> aggregated-distance-params post-processing
        (twin of :meth:`_fit`'s 1/scale weighting; sub-params are
        chunk-constant under the non-adaptive-subs fused gate)."""
        factors = jnp.asarray(self.factors, jnp.float32)
        sub_params = tuple(d.device_params(None) for d in self.distances)

        def post(scales):
            w = jnp.where(
                scales > 0, 1.0 / jnp.maximum(scales, 1e-38), 0.0
            )
            return (w * factors, sub_params)

        return post


def _span_of_values(values: np.ndarray) -> float:
    return float(np.max(values) - np.min(values))


class DistanceWithMeasureList(Distance):
    """Base for distances restricted to a list of sum-stat labels, calibrated
    from an initial prior sample (pyabc DistanceWithMeasureList)."""

    def __init__(self, measures_to_use: Sequence[str] | str = "all",
                 sumstat_spec: SumStatSpec | None = None):
        self.measures_to_use = measures_to_use
        self.spec = sumstat_spec
        self._cols: np.ndarray | None = None

    def requires_calibration(self) -> bool:
        return True

    def initialize(self, t, get_all_sum_stats=None, x_0=None):
        if self.spec is None and hasattr(x_0, "keys"):
            self.spec = SumStatSpec(x_0)
        labels = self.spec.labels() if self.spec else None
        if self.measures_to_use == "all" or labels is None:
            size = self.spec.total_size if self.spec else None
            self._cols = None if size is None else np.arange(size)
        else:
            cols = []
            for m in self.measures_to_use:
                if self.spec and m in self.spec.names:
                    off = self.spec.offsets[m]
                    cols.extend(range(off, off + self.spec.sizes[m]))
                elif labels and m in labels:
                    cols.append(labels.index(m))
                else:
                    raise KeyError(f"unknown measure {m!r}")
            self._cols = np.asarray(cols)
        if get_all_sum_stats is not None:
            samples = np.asarray(get_all_sum_stats(), np.float64)
            if self._cols is None:
                self._cols = np.arange(samples.shape[1])
            self._fit(samples[:, self._cols])

    def _select(self, x) -> np.ndarray:
        flat = _as_flat(x, self.spec)
        return flat if self._cols is None else flat[self._cols]

    def _fit(self, samples: np.ndarray) -> None:
        pass


class ZScoreDistance(DistanceWithMeasureList):
    """Relative deviation to the observation: sum |(x-x0)/x0| (pyabc ZScoreDistance)."""

    def __call__(self, x, x_0, t=None, par=None) -> float:
        xs, x0s = self._select(x), self._select(x_0)
        denom = np.where(x0s != 0, np.abs(x0s), 1.0)
        return float(np.sum(np.abs((xs - x0s) / denom)))


class PCADistance(DistanceWithMeasureList):
    """Euclidean distance in the PCA-whitened sum-stat space (pyabc PCADistance)."""

    def __init__(self, measures_to_use="all", sumstat_spec=None):
        super().__init__(measures_to_use, sumstat_spec)
        self._trafo: np.ndarray | None = None
        self._mean: np.ndarray | None = None

    def _fit(self, samples: np.ndarray) -> None:
        self._mean = samples.mean(axis=0)
        cov = np.cov(samples, rowvar=False)
        cov = np.atleast_2d(cov)
        vals, vecs = np.linalg.eigh(cov)
        vals = np.maximum(vals, 1e-12)
        # whitening transform: v -> diag(1/sqrt(vals)) @ vecs.T @ v
        self._trafo = (vecs / np.sqrt(vals)).T

    def __call__(self, x, x_0, t=None, par=None) -> float:
        if self._trafo is None:
            raise RuntimeError("PCADistance not initialized")
        diff = self._select(x) - self._select(x_0)
        return float(np.linalg.norm(self._trafo @ diff))


class RangeEstimatorDistance(DistanceWithMeasureList):
    """L1 distance normalized per-statistic by an estimated range
    (pyabc RangeEstimatorDistance); subclasses define the range."""

    @staticmethod
    def lower(samples: np.ndarray) -> np.ndarray:
        return np.min(samples, axis=0)

    @staticmethod
    def upper(samples: np.ndarray) -> np.ndarray:
        return np.max(samples, axis=0)

    def _fit(self, samples: np.ndarray) -> None:
        lo, hi = self.lower(samples), self.upper(samples)
        rng = hi - lo
        self._inv_range = np.where(rng > 0, 1.0 / np.where(rng > 0, rng, 1.0), 0.0)

    def __call__(self, x, x_0, t=None, par=None) -> float:
        diff = np.abs(self._select(x) - self._select(x_0))
        return float(np.sum(diff * self._inv_range))


class MinMaxDistance(RangeEstimatorDistance):
    """Range = [min, max] of the calibration sample (pyabc MinMaxDistance)."""


class PercentileDistance(RangeEstimatorDistance):
    """Range = inner percentile interval (pyabc PercentileDistance)."""

    PERCENTILE = 1  # as in the reference

    @classmethod
    def lower(cls, samples):
        return np.percentile(samples, cls.PERCENTILE, axis=0)

    @classmethod
    def upper(cls, samples):
        return np.percentile(samples, 100 - cls.PERCENTILE, axis=0)
