"""Stochastic kernels — noise-model "distances" for noisy/stochastic ABC.

Reference parity: ``pyabc/distance/kernel.py::{StochasticKernel, NormalKernel,
IndependentNormalKernel, IndependentLaplaceKernel, BinomialKernel,
PoissonKernel, NegativeBinomialKernel}`` with ``ret_scale`` in
{SCALE_LIN, SCALE_LOG}.

A kernel returns the (log-)density of the observation x_0 under a noise model
centered at the simulation x. `StochasticAcceptor` consumes these, accepting
with probability proportional to density^(1/T). All kernels here default to
SCALE_LOG (the numerically sane choice); device forms are traceable jnp.
"""
from __future__ import annotations

import math
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.sumstat_spec import SumStatSpec
from .base import Distance
from .pnorm import _as_flat

SCALE_LIN = "SCALE_LIN"
SCALE_LOG = "SCALE_LOG"

_LOG_2PI = math.log(2.0 * math.pi)

#: absolute slack (log-density units) on the stochastic retirement
#: comparison: the engine's running prefix sum and the full-vector
#: ``jnp.sum`` may round in different orders, so a bound within this
#: band of the lane's log-density threshold never retires. A false keep
#: wastes segments; a false retire would be unsound. The relative term
#: covers large-magnitude log-densities (1e-4 is ~200x the f32
#: summation error of a 10^4-entry sum).
BOUND_SLACK = 1e-3


def _upper_exceeds(acc, threshold, params):
    """Retirement test of an UPPER log-density bound: True only when the
    final log-density is provably BELOW ``threshold`` — i.e. acceptance
    at that per-lane log-density threshold is impossible."""
    slack = BOUND_SLACK + 1e-4 * jnp.abs(acc)
    return acc < threshold - slack


class StochasticKernel(Distance):
    """Base stochastic kernel (pyabc StochasticKernel).

    ``ret_scale`` declares whether __call__ returns the density (SCALE_LIN)
    or its log (SCALE_LOG). ``pdf_max`` is the (log-)maximum of the density
    over x for acceptance normalization — computed at initialize if the
    subclass can.
    """

    def __init__(self, ret_scale: str = SCALE_LOG,
                 keys: Sequence[str] | None = None,
                 pdf_max: float | None = None,
                 sumstat_spec: SumStatSpec | None = None):
        if ret_scale not in (SCALE_LIN, SCALE_LOG):
            raise ValueError(f"ret_scale must be SCALE_LIN/SCALE_LOG: {ret_scale}")
        self.ret_scale = ret_scale
        self.keys = keys
        self.pdf_max = pdf_max
        self.spec = sumstat_spec

    def initialize(self, t, get_all_sum_stats=None, x_0=None):
        if self.spec is None and hasattr(x_0, "keys"):
            if self.keys is not None:
                x_0 = {k: x_0[k] for k in self.keys}
            self.spec = SumStatSpec(x_0)

    def _flat(self, x) -> np.ndarray:
        if self.keys is not None and hasattr(x, "keys"):
            x = {k: x[k] for k in self.keys}
        return _as_flat(x, self.spec)

    def is_device_compatible(self) -> bool:
        return True


class NormalKernel(StochasticKernel):
    """Multivariate normal noise with full covariance (pyabc NormalKernel)."""

    def __init__(self, cov=None, ret_scale: str = SCALE_LOG, keys=None,
                 sumstat_spec=None):
        super().__init__(ret_scale, keys, None, sumstat_spec)
        self._cov_arg = cov
        self._prec = None
        self._logdet = None
        self._dim = None

    def initialize(self, t, get_all_sum_stats=None, x_0=None):
        super().initialize(t, get_all_sum_stats, x_0)
        dim = self.spec.total_size if self.spec else np.size(
            self._flat(x_0)
        )
        cov = self._cov_arg if self._cov_arg is not None else np.eye(dim)
        cov = np.atleast_2d(np.asarray(cov, np.float64))
        self._dim = cov.shape[0]
        self._prec = np.linalg.inv(cov)
        sign, logdet = np.linalg.slogdet(cov)
        if sign <= 0:
            raise ValueError("kernel covariance must be positive definite")
        self._logdet = logdet
        # log max over x: the mode value
        self.pdf_max = -0.5 * (self._dim * _LOG_2PI + self._logdet)
        if self.ret_scale == SCALE_LIN:
            self.pdf_max = math.exp(self.pdf_max)

    def __call__(self, x, x_0, t=None, par=None) -> float:
        diff = self._flat(x) - self._flat(x_0)
        logp = -0.5 * (
            self._dim * _LOG_2PI + self._logdet + diff @ self._prec @ diff
        )
        return float(np.exp(logp)) if self.ret_scale == SCALE_LIN else float(logp)

    def device_params(self, t=None):
        return (jnp.asarray(self._prec, jnp.float32),
                jnp.asarray(self._logdet, jnp.float32))

    def device_fn(self, spec):
        dim = self._dim
        lin = self.ret_scale == SCALE_LIN

        def fn(x, x0, params):
            prec, logdet = params
            diff = x - x0
            logp = -0.5 * (dim * _LOG_2PI + logdet + diff @ prec @ diff)
            return jnp.exp(logp) if lin else logp

        return fn


class IndependentNormalKernel(StochasticKernel):
    """Independent normal noise per statistic (pyabc IndependentNormalKernel).

    ``var`` may be a scalar, a vector, or a callable ``var(par) -> vector``
    (parameterized noise — e.g. an inferred noise parameter).
    """

    def __init__(self, var=None, keys=None, sumstat_spec=None):
        super().__init__(SCALE_LOG, keys, None, sumstat_spec)
        self.var = var

    def is_device_compatible(self) -> bool:
        # parameterized noise (callable var) has no generic device form
        return not callable(self.var)

    def initialize(self, t, get_all_sum_stats=None, x_0=None):
        super().initialize(t, get_all_sum_stats, x_0)
        dim = self.spec.total_size if self.spec else np.size(self._flat(x_0))
        if self.var is None:
            self.var = np.ones(dim)
        if not callable(self.var):
            var = np.broadcast_to(np.asarray(self.var, np.float64), (dim,))
            self.pdf_max = float(-0.5 * np.sum(_LOG_2PI + np.log(var)))

    def _var_for(self, par):
        if callable(self.var):
            return np.ravel(np.asarray(self.var(par), np.float64))
        return np.ravel(np.asarray(self.var, np.float64))

    def __call__(self, x, x_0, t=None, par=None) -> float:
        diff = self._flat(x) - self._flat(x_0)
        var = np.broadcast_to(self._var_for(par), diff.shape)
        return float(-0.5 * np.sum(_LOG_2PI + np.log(var) + diff * diff / var))

    def device_params(self, t=None):
        if callable(self.var):
            return ()
        return jnp.asarray(np.ravel(np.asarray(self.var, np.float64)), jnp.float32)

    def device_fn(self, spec):
        var_callable = callable(self.var)

        def fn(x, x0, params):
            diff = x - x0
            var = jnp.broadcast_to(params, diff.shape)
            return -0.5 * jnp.sum(_LOG_2PI + jnp.log(var) + diff * diff / var)

        if var_callable:
            raise NotImplementedError(
                "callable var: use device_fn_par (parameterized noise)"
            )
        return fn

    def device_fn_par(self, spec):
        """Parameterized-noise device form: fn(x, x0, var_vector)."""
        def fn(x, x0, var):
            diff = x - x0
            var = jnp.broadcast_to(var, diff.shape)
            return -0.5 * jnp.sum(_LOG_2PI + jnp.log(var) + diff * diff / var)
        return fn

    def device_bound_fn(self, spec):
        """Monotone UPPER-bound accumulator on the total log-density over
        sum-stat prefixes (the stochastic-acceptor retirement contract).

        Each element's log-density is maximized at zero deviation
        (``-0.5 (log 2π + log var_i)``), so the accumulator starts at
        ``pdf_max`` (the sum of per-element maxima) and every emitted
        entry subtracts its actual deficit ``0.5 diff²/var`` — the bound
        is non-increasing as entries fold in and always ≥ the final
        log-density. ``exceeds`` fires only when even this optimistic
        bound sits below the lane's acceptance threshold."""
        if callable(self.var) or self.pdf_max is None:
            return None
        pdf_max = float(self.pdf_max)

        def init():
            return jnp.asarray(pdf_max, jnp.float32)

        def step(acc, vals, idx, x0, params):
            var = jnp.broadcast_to(params, x0.shape)[idx]
            diff = vals - x0[idx]
            return acc - 0.5 * jnp.sum(diff * diff / var)

        return {"init": init, "step": step, "exceeds": _upper_exceeds,
                "upper": True}


class IndependentLaplaceKernel(StochasticKernel):
    """Independent Laplace noise per statistic (pyabc IndependentLaplaceKernel)."""

    def __init__(self, scale=None, keys=None, sumstat_spec=None):
        super().__init__(SCALE_LOG, keys, None, sumstat_spec)
        self.scale = scale

    def is_device_compatible(self) -> bool:
        return not callable(self.scale)

    def initialize(self, t, get_all_sum_stats=None, x_0=None):
        super().initialize(t, get_all_sum_stats, x_0)
        dim = self.spec.total_size if self.spec else np.size(self._flat(x_0))
        if self.scale is None:
            self.scale = np.ones(dim)
        if not callable(self.scale):
            b = np.broadcast_to(np.asarray(self.scale, np.float64), (dim,))
            self.pdf_max = float(-np.sum(np.log(2.0 * b)))

    def __call__(self, x, x_0, t=None, par=None) -> float:
        diff = self._flat(x) - self._flat(x_0)
        b = self.scale(par) if callable(self.scale) else self.scale
        b = np.broadcast_to(np.ravel(np.asarray(b, np.float64)), diff.shape)
        return float(-np.sum(np.log(2.0 * b) + np.abs(diff) / b))

    def device_params(self, t=None):
        return jnp.asarray(np.ravel(np.asarray(self.scale, np.float64)),
                           jnp.float32)

    def device_fn(self, spec):
        def fn(x, x0, params):
            diff = x - x0
            b = jnp.broadcast_to(params, diff.shape)
            return -jnp.sum(jnp.log(2.0 * b) + jnp.abs(diff) / b)
        return fn

    def device_bound_fn(self, spec):
        """Monotone UPPER-bound accumulator on the total log-density:
        start at ``pdf_max`` (per-element maxima ``-log 2b_i``), subtract
        each emitted entry's deficit ``|diff|/b`` — non-increasing, and
        always ≥ the final log-density."""
        if callable(self.scale) or self.pdf_max is None:
            return None
        pdf_max = float(self.pdf_max)

        def init():
            return jnp.asarray(pdf_max, jnp.float32)

        def step(acc, vals, idx, x0, params):
            b = jnp.broadcast_to(params, x0.shape)[idx]
            return acc - jnp.sum(jnp.abs(vals - x0[idx]) / b)

        return {"init": init, "step": step, "exceeds": _upper_exceeds,
                "upper": True}


def _binom_logpmf(k, n, p):
    return (
        jax.scipy.special.gammaln(n + 1.0)
        - jax.scipy.special.gammaln(k + 1.0)
        - jax.scipy.special.gammaln(n - k + 1.0)
        + jax.scipy.special.xlogy(k, p)
        + jax.scipy.special.xlog1py(n - k, -p)
    )


class BinomialKernel(StochasticKernel):
    """Binomial observation noise: x_0 ~ Binom(n=sim, p) (pyabc BinomialKernel)."""

    def __init__(self, p: float, ret_scale: str = SCALE_LOG, keys=None,
                 sumstat_spec=None):
        if not 0 < p <= 1:
            raise ValueError("p must be in (0, 1]")
        super().__init__(ret_scale, keys, 0.0 if ret_scale == SCALE_LOG else 1.0,
                         sumstat_spec)
        self.p = float(p)

    def __call__(self, x, x_0, t=None, par=None) -> float:
        from scipy.stats import binom

        n = np.maximum(np.round(self._flat(x)), 0.0)
        k = np.round(self._flat(x_0))
        logp = binom.logpmf(k, n, self.p)
        total = float(np.sum(logp))
        return math.exp(total) if self.ret_scale == SCALE_LIN else total

    def device_params(self, t=None):
        return jnp.asarray(self.p, jnp.float32)

    def device_fn(self, spec):
        lin = self.ret_scale == SCALE_LIN

        def fn(x, x0, p):
            n = jnp.maximum(jnp.round(x), 0.0)
            k = jnp.round(x0)
            logp = _binom_logpmf(k, n, p)
            logp = jnp.where((k >= 0) & (k <= n), logp, -jnp.inf)
            total = jnp.sum(logp)
            return jnp.exp(total) if lin else total

        return fn

    def device_bound_fn(self, spec):
        """Monotone UPPER bound: every per-element log-pmf is ≤ 0 (a pmf
        never exceeds 1), so the prefix partial sum of ACTUAL per-entry
        log-pmfs upper-bounds the total — init 0, fold the real terms.
        SCALE_LOG only (the lin path's 1e-30 density clamp happens after
        the sum and is not prefix-separable)."""
        if self.ret_scale != SCALE_LOG:
            return None

        def init():
            return jnp.zeros((), jnp.float32)

        def step(acc, vals, idx, x0, p):
            n = jnp.maximum(jnp.round(vals), 0.0)
            k = jnp.round(x0[idx])
            logp = _binom_logpmf(k, n, p)
            logp = jnp.where((k >= 0) & (k <= n), logp, -jnp.inf)
            return acc + jnp.sum(logp)

        return {"init": init, "step": step, "exceeds": _upper_exceeds,
                "upper": True}


class PoissonKernel(StochasticKernel):
    """Poisson observation noise: x_0 ~ Poisson(sim) (pyabc PoissonKernel)."""

    def __init__(self, ret_scale: str = SCALE_LOG, keys=None, sumstat_spec=None):
        super().__init__(ret_scale, keys, 0.0 if ret_scale == SCALE_LOG else 1.0,
                         sumstat_spec)

    def __call__(self, x, x_0, t=None, par=None) -> float:
        lam = np.maximum(self._flat(x), 1e-12)
        k = np.round(self._flat(x_0))
        from scipy.special import gammaln

        logp = k * np.log(lam) - lam - gammaln(k + 1.0)
        logp = np.where(k >= 0, logp, -np.inf)
        total = float(np.sum(logp))
        return math.exp(total) if self.ret_scale == SCALE_LIN else total

    def device_params(self, t=None):
        return ()

    def device_fn(self, spec):
        lin = self.ret_scale == SCALE_LIN

        def fn(x, x0, params):
            lam = jnp.maximum(x, 1e-12)
            k = jnp.round(x0)
            logp = k * jnp.log(lam) - lam - jax.scipy.special.gammaln(k + 1.0)
            logp = jnp.where(k >= 0, logp, -jnp.inf)
            total = jnp.sum(logp)
            return jnp.exp(total) if lin else total

        return fn

    def device_bound_fn(self, spec):
        """Monotone UPPER bound via the pmf ≤ 1 argument — see
        :meth:`BinomialKernel.device_bound_fn`."""
        if self.ret_scale != SCALE_LOG:
            return None

        def init():
            return jnp.zeros((), jnp.float32)

        def step(acc, vals, idx, x0, params):
            lam = jnp.maximum(vals, 1e-12)
            k = jnp.round(x0[idx])
            logp = (k * jnp.log(lam) - lam
                    - jax.scipy.special.gammaln(k + 1.0))
            logp = jnp.where(k >= 0, logp, -jnp.inf)
            return acc + jnp.sum(logp)

        return {"init": init, "step": step, "exceeds": _upper_exceeds,
                "upper": True}


class NegativeBinomialKernel(StochasticKernel):
    """Negative-binomial observation noise with dispersion p
    (pyabc NegativeBinomialKernel).

    ``parameterization="size"`` (default, reference-faithful): the simulated
    value is passed directly as the NB size parameter n, i.e.
    ``nbinom.pmf(k=x_0, n=sim, p)`` — matching
    ``pyabc/distance/kernel.py::NegativeBinomialKernel``.
    ``parameterization="mean"``: the simulated value is the NB *mean*,
    n = mean * p / (1 - p) — often the more natural modeling choice, but a
    deviation from the reference; opt in explicitly.
    """

    def __init__(self, p: float, ret_scale: str = SCALE_LOG, keys=None,
                 sumstat_spec=None, parameterization: str = "size"):
        super().__init__(ret_scale, keys, None, sumstat_spec)
        self.p = float(p)
        if parameterization not in ("size", "mean"):
            raise ValueError(
                f"parameterization must be 'size' or 'mean', got "
                f"{parameterization!r}"
            )
        self.parameterization = parameterization

    def _size(self, x):
        x = np.maximum(x, 1e-12)
        if self.parameterization == "mean":
            return x * self.p / (1.0 - self.p)
        return x

    def __call__(self, x, x_0, t=None, par=None) -> float:
        n = self._size(self._flat(x))
        k = np.round(self._flat(x_0))
        from scipy.special import gammaln

        logp = (
            gammaln(k + n) - gammaln(n) - gammaln(k + 1.0)
            + n * np.log(self.p) + k * np.log1p(-self.p)
        )
        logp = np.where(k >= 0, logp, -np.inf)
        total = float(np.sum(logp))
        return math.exp(total) if self.ret_scale == SCALE_LIN else total

    def device_params(self, t=None):
        return jnp.asarray(self.p, jnp.float32)

    def device_fn(self, spec):
        lin = self.ret_scale == SCALE_LIN
        mean_param = self.parameterization == "mean"

        def fn(x, x0, p):
            x = jnp.maximum(x, 1e-12)
            n = x * p / (1.0 - p) if mean_param else x
            k = jnp.round(x0)
            logp = (
                jax.scipy.special.gammaln(k + n)
                - jax.scipy.special.gammaln(n)
                - jax.scipy.special.gammaln(k + 1.0)
                + n * jnp.log(p)
                + k * jnp.log1p(-p)
            )
            logp = jnp.where(k >= 0, logp, -jnp.inf)
            total = jnp.sum(logp)
            return jnp.exp(total) if lin else total

        return fn


class FunctionKernel(StochasticKernel):
    """Adapter: arbitrary density function as a kernel (pyabc FunctionKernel)."""

    def __init__(self, fn: Callable, ret_scale: str = SCALE_LOG,
                 pdf_max=None):
        super().__init__(ret_scale, None, pdf_max)
        self.fn = fn

    def __call__(self, x, x_0, t=None, par=None):
        return self.fn(x, x_0, t, par)

    def is_device_compatible(self) -> bool:
        return False
