"""Scale functions for adaptive distance re-weighting.

Reference parity: ``pyabc/distance/scale.py`` — the pluggable per-statistic
scale estimators used by ``AdaptivePNormDistance`` (weight = 1/scale).

TPU-first shift: the reference computes these per sum-stat key over a list of
dicts; here each function is vectorized over the statistic axis — input is the
full matrix ``samples: (n_samples, S)`` of flattened sum stats plus the
observed ``x_0: (S,)``, output is a ``(S,)`` scale vector. numpy float64 on
host (runs once per generation).
"""
from __future__ import annotations

import numpy as np


def median_absolute_deviation(samples, x_0=None):
    """MAD: median(|x - median(x)|) per statistic."""
    med = np.median(samples, axis=0)
    return np.median(np.abs(samples - med), axis=0)


def mean_absolute_deviation(samples, x_0=None):
    mean = np.mean(samples, axis=0)
    return np.mean(np.abs(samples - mean), axis=0)


def standard_deviation(samples, x_0=None):
    return np.std(samples, axis=0)


def span(samples, x_0=None):
    return np.max(samples, axis=0) - np.min(samples, axis=0)


def mean(samples, x_0=None):
    return np.mean(samples, axis=0)


def median(samples, x_0=None):
    return np.median(samples, axis=0)


def bias(samples, x_0):
    """|mean(x) - x_0| — systematic deviation from the observation."""
    return np.abs(np.mean(samples, axis=0) - x_0)


def root_mean_square_deviation(samples, x_0):
    """sqrt(bias^2 + std^2) — total deviation around the observation."""
    b = bias(samples, x_0)
    s = standard_deviation(samples)
    return np.sqrt(b * b + s * s)


def median_absolute_deviation_to_observation(samples, x_0):
    return np.median(np.abs(samples - x_0), axis=0)


def mean_absolute_deviation_to_observation(samples, x_0):
    return np.mean(np.abs(samples - x_0), axis=0)


def combined_median_absolute_deviation(samples, x_0):
    """MAD + |median - x_0| (reference combined_median_absolute_deviation)."""
    return median_absolute_deviation(samples) + np.abs(
        np.median(samples, axis=0) - x_0
    )


def combined_mean_absolute_deviation(samples, x_0):
    return mean_absolute_deviation(samples) + np.abs(
        np.mean(samples, axis=0) - x_0
    )


def standard_deviation_to_observation(samples, x_0):
    """sqrt(mean((x - x_0)^2)) around the observation."""
    return np.sqrt(np.mean((samples - x_0) ** 2, axis=0))


SCALE_FUNCTIONS = {
    f.__name__: f
    for f in [
        median_absolute_deviation,
        mean_absolute_deviation,
        standard_deviation,
        span,
        mean,
        median,
        bias,
        root_mean_square_deviation,
        median_absolute_deviation_to_observation,
        mean_absolute_deviation_to_observation,
        combined_median_absolute_deviation,
        combined_mean_absolute_deviation,
        standard_deviation_to_observation,
    ]
}
