"""Scale functions for adaptive distance re-weighting.

Reference parity: ``pyabc/distance/scale.py`` — the pluggable per-statistic
scale estimators used by ``AdaptivePNormDistance`` (weight = 1/scale).

TPU-first shift: the reference computes these per sum-stat key over a list of
dicts; here each function is vectorized over the statistic axis — input is the
full matrix ``samples: (n_samples, S)`` of flattened sum stats plus the
observed ``x_0: (S,)``, output is a ``(S,)`` scale vector. numpy float64 on
host (runs once per generation).
"""
from __future__ import annotations

import numpy as np


def median_absolute_deviation(samples, x_0=None):
    """MAD: median(|x - median(x)|) per statistic."""
    med = np.median(samples, axis=0)
    return np.median(np.abs(samples - med), axis=0)


def mean_absolute_deviation(samples, x_0=None):
    mean = np.mean(samples, axis=0)
    return np.mean(np.abs(samples - mean), axis=0)


def standard_deviation(samples, x_0=None):
    return np.std(samples, axis=0)


def span(samples, x_0=None):
    return np.max(samples, axis=0) - np.min(samples, axis=0)


def mean(samples, x_0=None):
    return np.mean(samples, axis=0)


def median(samples, x_0=None):
    return np.median(samples, axis=0)


def bias(samples, x_0):
    """|mean(x) - x_0| — systematic deviation from the observation."""
    return np.abs(np.mean(samples, axis=0) - x_0)


def root_mean_square_deviation(samples, x_0):
    """sqrt(bias^2 + std^2) — total deviation around the observation."""
    b = bias(samples, x_0)
    s = standard_deviation(samples)
    return np.sqrt(b * b + s * s)


def median_absolute_deviation_to_observation(samples, x_0):
    return np.median(np.abs(samples - x_0), axis=0)


def mean_absolute_deviation_to_observation(samples, x_0):
    return np.mean(np.abs(samples - x_0), axis=0)


def combined_median_absolute_deviation(samples, x_0):
    """MAD + |median - x_0| (reference combined_median_absolute_deviation)."""
    return median_absolute_deviation(samples) + np.abs(
        np.median(samples, axis=0) - x_0
    )


def combined_mean_absolute_deviation(samples, x_0):
    return mean_absolute_deviation(samples) + np.abs(
        np.mean(samples, axis=0) - x_0
    )


def standard_deviation_to_observation(samples, x_0):
    """sqrt(mean((x - x_0)^2)) around the observation."""
    return np.sqrt(np.mean((samples - x_0) ** 2, axis=0))


SCALE_FUNCTIONS = {
    f.__name__: f
    for f in [
        median_absolute_deviation,
        mean_absolute_deviation,
        standard_deviation,
        span,
        mean,
        median,
        bias,
        root_mean_square_deviation,
        median_absolute_deviation_to_observation,
        mean_absolute_deviation_to_observation,
        combined_median_absolute_deviation,
        combined_mean_absolute_deviation,
        standard_deviation_to_observation,
    ]
}


# ---------------------------------------------------------------------------
# Device twins: masked reductions over the ON-DEVICE record ring.
#
# The fused generation kernel keeps all evaluated sum stats in a device
# reservoir; fetching it to the host costs ~100ms/MB over a TPU tunnel while
# the reduction itself is microseconds of VPU time. Each twin takes the
# UNMASKED ring ``samples (n, S)`` + ``valid (n,)`` + ``x_0 (S,)`` and
# returns the (S,) scale vector; only those S floats cross the wire.
# Masked medians go through NaN-substitution + nanquantile.
# ---------------------------------------------------------------------------

def _device_scale_impls():
    import jax.numpy as jnp

    def _masked(samples, valid):
        return jnp.where(valid[:, None], samples, jnp.nan)

    def _nanmedian(x):
        return jnp.nanquantile(x, 0.5, axis=0)

    def _mean(samples, valid):
        n = jnp.maximum(valid.sum(), 1)
        return jnp.where(valid[:, None], samples, 0.0).sum(axis=0) / n

    def _std(samples, valid):
        mu = _mean(samples, valid)
        n = jnp.maximum(valid.sum(), 1)
        var = (jnp.where(valid[:, None], (samples - mu) ** 2, 0.0).sum(axis=0)
               / n)
        return jnp.sqrt(var)

    def mad(samples, valid, x_0):
        m = _masked(samples, valid)
        med = _nanmedian(m)
        return _nanmedian(jnp.abs(m - med))

    def mean_ad(samples, valid, x_0):
        mu = _mean(samples, valid)
        n = jnp.maximum(valid.sum(), 1)
        return (jnp.where(valid[:, None], jnp.abs(samples - mu), 0.0)
                .sum(axis=0) / n)

    def std(samples, valid, x_0):
        return _std(samples, valid)

    def span_(samples, valid, x_0):
        big = jnp.where(valid[:, None], samples, -jnp.inf).max(axis=0)
        small = jnp.where(valid[:, None], samples, jnp.inf).min(axis=0)
        return big - small

    def mean_(samples, valid, x_0):
        return _mean(samples, valid)

    def median_(samples, valid, x_0):
        return _nanmedian(_masked(samples, valid))

    def bias_(samples, valid, x_0):
        return jnp.abs(_mean(samples, valid) - x_0)

    def rmsd(samples, valid, x_0):
        b = bias_(samples, valid, x_0)
        s = _std(samples, valid)
        return jnp.sqrt(b * b + s * s)

    def mad_to_obs(samples, valid, x_0):
        return _nanmedian(jnp.abs(_masked(samples, valid) - x_0))

    def mean_ad_to_obs(samples, valid, x_0):
        n = jnp.maximum(valid.sum(), 1)
        return (jnp.where(valid[:, None], jnp.abs(samples - x_0), 0.0)
                .sum(axis=0) / n)

    def combined_mad(samples, valid, x_0):
        m = _masked(samples, valid)
        return mad(samples, valid, x_0) + jnp.abs(_nanmedian(m) - x_0)

    def combined_mean_ad(samples, valid, x_0):
        return (mean_ad(samples, valid, x_0)
                + jnp.abs(_mean(samples, valid) - x_0))

    def std_to_obs(samples, valid, x_0):
        n = jnp.maximum(valid.sum(), 1)
        return jnp.sqrt(
            jnp.where(valid[:, None], (samples - x_0) ** 2, 0.0).sum(axis=0)
            / n
        )

    return {
        "median_absolute_deviation": mad,
        "mean_absolute_deviation": mean_ad,
        "standard_deviation": std,
        "span": span_,
        "mean": mean_,
        "median": median_,
        "bias": bias_,
        "root_mean_square_deviation": rmsd,
        "median_absolute_deviation_to_observation": mad_to_obs,
        "mean_absolute_deviation_to_observation": mean_ad_to_obs,
        "combined_median_absolute_deviation": combined_mad,
        "combined_mean_absolute_deviation": combined_mean_ad,
        "standard_deviation_to_observation": std_to_obs,
    }


_device_scale_cache: dict = {}


def device_scale_fn(name: str):
    """Jitted masked device twin of the named scale function, or None.

    Signature: ``fn(samples (n,S) f32, valid (n,) bool, x_0 (S,)) -> (S,)``.
    """
    if name in _device_scale_cache:
        return _device_scale_cache[name]
    impls = _device_scale_impls()
    if name not in impls:
        _device_scale_cache[name] = None
        return None
    import jax

    fn = jax.jit(impls[name])
    _device_scale_cache[name] = fn
    return fn
