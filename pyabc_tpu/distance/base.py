"""Distance base classes.

Reference parity: ``pyabc/distance/base.py::{Distance, NoDistance,
IdentityFakeDistance, AcceptAllDistance, SimpleFunctionDistance}``.

TPU-first contract (SURVEY.md §7.1): besides the reference's host API
(``__call__(x, x_0, t, par)`` on sum-stat dicts), every distance that can run
on-device exposes

- ``device_params(t) -> pytree of jnp arrays`` — the per-generation state
  (e.g. adaptive weights), passed as *arguments* into the jitted generation
  kernel so weight updates never trigger recompilation;
- ``device_fn(spec) -> fn(x_flat, x0_flat, params) -> scalar`` — a traceable
  distance over flat sum-stat vectors, vmapped by the kernel.

Host ``__call__`` remains the semantic source of truth and is what unit tests
check against closed forms.
"""
from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Callable

import jax.numpy as jnp
import numpy as np

from ..core.sumstat_spec import SumStatSpec


class Distance(ABC):
    """Abstract distance (pyabc Distance).

    Lifecycle driven by the ABCSMC orchestrator: ``initialize`` before gen 0,
    ``configure_sampler`` once, ``update`` after every generation (returns
    True if the distance changed).
    """

    def initialize(self, t: int, get_all_sum_stats: Callable | None = None,
                   x_0=None) -> None:
        pass

    def configure_sampler(self, sampler) -> None:
        """Request sampler capabilities (e.g. record rejected sum stats)."""

    def update(self, t: int, get_all_sum_stats: Callable | None = None) -> bool:
        return False

    @abstractmethod
    def __call__(self, x, x_0, t: int | None = None, par=None) -> float:
        """Distance between sum-stat dicts x and x_0."""

    # ---------------------------------------------------------------- device
    def is_device_compatible(self) -> bool:
        return False

    def device_params(self, t: int | None = None):
        """Per-generation parameter pytree passed into the kernel."""
        return ()

    def device_fn(self, spec: SumStatSpec):
        """Traceable ``fn(x_flat, x0_flat, params) -> scalar distance``."""
        raise NotImplementedError(f"{type(self).__name__} has no device form")

    def device_bound_fn(self, spec: SumStatSpec):
        """Optional monotone lower-bound accumulator over sum-stat
        PREFIXES — the soundness contract of the segmented early-reject
        engine (ISSUE 15). Returns None (default: no sound bound, the
        early-reject mode gates off) or a dict of traceable closures:

        - ``init() -> acc`` — the empty-prefix accumulator;
        - ``step(acc, vals (k,), idx (k,) int32, x0 (S,), params) ->
          acc`` — fold the newly emitted flat entries ``vals`` at flat
          positions ``idx`` into the accumulator;
        - ``exceeds(acc, threshold, params) -> bool`` — True only when
          the FINAL distance over the complete vector is provably above
          ``threshold`` (a small relative slack must absorb
          summation-order ULP effects: a false keep wastes work, a
          false retire would be unsound).

        Monotonicity requirement: folding more entries must never
        decrease the implied bound — p-norms with non-negative weights
        qualify; signed/normalized forms do not.

        Stochastic kernels flip the direction: a closure dict carrying
        ``"upper": True`` is a monotone UPPER bound on the total
        LOG-DENSITY, and its ``exceeds(acc, thr, params)`` is True only
        when acceptance at the per-lane log-density threshold ``thr`` is
        provably impossible (``acc < thr`` minus slack). The engine
        computes ``thr`` from the lane's pre-committed acceptance draw —
        upper-bound closures are only sound under a
        :class:`~pyabc_tpu.acceptor.StochasticAcceptor` and the
        segmented engine refuses them elsewhere.
        """
        return None

    def device_record_reduce(self, spec: SumStatSpec):
        """Optional traceable reduction folded into the generation kernel:
        ``fn(rec_sumstats (n,S), rec_valid (n,), x0 (S,)) -> (S,)``.

        Adaptive distances use it to compute their per-statistic scale ON
        DEVICE so the record ring never crosses the host link (one extra
        sync over a TPU tunnel costs more than the whole reduction)."""
        return None

    def sharded_scale_capable(self) -> bool:
        """True when this distance's adaptive scale refit decomposes into
        per-shard partial moments (``ops/scale_reduce.py``) so the
        SHARDED multigen kernel can serve it with scalar-per-stat
        collectives only. Non-adaptive distances return False — they
        have no scale refit to shard (the sharded gate treats them as
        trivially capable)."""
        return False

    def device_sharded_reduce(self, spec: SumStatSpec):
        """Sharded counterpart of :meth:`device_record_reduce`: the
        moment-expressed scale reduction config for the sharded multigen
        kernel, or None. Keys: ``cols`` (optional batched
        ``fn(rec_ss (n,S), x0 (S,)) -> (n, C)`` record-column transform;
        None = raw sum stats), ``x0_cols`` (the (C,) observation in
        column space; None = the kernel's x0), ``name`` (the validated
        scale-function name for
        :func:`~pyabc_tpu.ops.scale_reduce.scale_from_moments`) and
        ``moment_rows``/``cols_dim`` (static collective-payload
        accounting for the mesh observability block)."""
        return None

    def requires_calibration(self) -> bool:
        """True if initialize() needs a prior calibration sample."""
        return False

    def get_config(self) -> dict:
        return {"name": type(self).__name__}

    def __repr__(self):
        return f"{type(self).__name__}()"


class NoDistance(Distance):
    """Placeholder that must never be evaluated (pyabc NoDistance)."""

    def __call__(self, x, x_0, t=None, par=None) -> float:
        raise RuntimeError("NoDistance must not be called")


class IdentityFakeDistance(Distance):
    """Passes the simulation through as 'distance' (pyabc IdentityFakeDistance).

    Used when the model itself computes and returns a distance.
    """

    def __call__(self, x, x_0, t=None, par=None):
        return x


class AcceptAllDistance(Distance):
    """Always distance -1 < any epsilon (pyabc AcceptAllDistance)."""

    def __call__(self, x, x_0, t=None, par=None) -> float:
        return -1.0

    def is_device_compatible(self) -> bool:
        return True

    def device_fn(self, spec):
        def fn(x, x0, params):
            return jnp.asarray(-1.0, jnp.float32)
        return fn


class SimpleFunctionDistance(Distance):
    """Adapter for a plain callable ``f(x, x_0) -> float``
    (pyabc SimpleFunctionDistance / to_distance)."""

    def __init__(self, fn: Callable, traceable: bool = False):
        self.fn = fn
        self.traceable = traceable

    def __call__(self, x, x_0, t=None, par=None) -> float:
        return self.fn(x, x_0)

    def is_device_compatible(self) -> bool:
        return self.traceable

    def device_fn(self, spec):
        if not self.traceable:
            raise NotImplementedError(
                "wrap with to_distance(fn, traceable=True) for device use"
            )
        f = self.fn

        def fn(x, x0, params):
            return f(spec.unflatten_traceable(x), spec.unflatten_traceable(x0))

        return fn

    def __repr__(self):
        return f"SimpleFunctionDistance({getattr(self.fn, '__name__', self.fn)!r})"


def to_distance(maybe_distance, traceable: bool = False) -> Distance | None:
    """Coerce None/callable/Distance into a Distance (pyabc to_distance)."""
    if maybe_distance is None:
        return None
    if isinstance(maybe_distance, Distance):
        return maybe_distance
    if callable(maybe_distance):
        return SimpleFunctionDistance(maybe_distance, traceable=traceable)
    raise TypeError(f"cannot coerce {maybe_distance!r} into a Distance")
