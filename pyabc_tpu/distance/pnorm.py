"""Weighted p-norm distances, fixed and adaptive.

Reference parity: ``pyabc/distance/distance.py::{PNormDistance,
AdaptivePNormDistance}`` and the scale functions of
``pyabc/distance/scale.py``.

Semantics (reference): d(x, x0) = (sum_i (w_i |x_i - x0_i|)^p)^(1/p);
p = inf gives max_i w_i |x_i - x0_i|. The adaptive variant refits w_i each
generation as 1/scale_i over ALL simulations of the previous generation
(accepted AND rejected — ``configure_sampler`` sets
``sampler.sample_factory.record_rejected = True``), optionally normalized to
mean 1 so epsilon magnitudes stay comparable across generations.

Device form: weights are a ``(S,)`` jnp vector passed as a kernel argument,
so per-generation reweighting never recompiles the generation kernel.
"""
from __future__ import annotations

import json
from typing import Callable, Mapping

import jax.numpy as jnp
import numpy as np

from ..core.sumstat_spec import SumStatSpec
from .base import Distance
from .scale import median_absolute_deviation


def _as_flat(x, spec: SumStatSpec | None) -> np.ndarray:
    """Dict-or-vector sum stats -> flat float64 vector (host path)."""
    if isinstance(x, Mapping):
        if spec is not None:
            # flatten_host, not flatten: this runs inside forked sampler
            # workers where a jnp op would initialize a JAX backend and
            # deadlock (fork-after-XLA-init)
            return np.asarray(spec.flatten_host(x), np.float64)
        parts = [np.ravel(np.asarray(x[k], np.float64)) for k in sorted(x)]
        return np.concatenate(parts) if len(parts) > 1 else parts[0]
    return np.ravel(np.asarray(x, np.float64))


class PNormDistance(Distance):
    """Fixed-weight weighted p-norm (pyabc PNormDistance).

    ``weights`` may be a dict ``{t: vector-or-dict}`` (per-generation), a flat
    vector, or a dict keyed by sum-stat label; None means all-ones.
    """

    def __init__(self, p: float = 2.0, weights=None,
                 factors=None, sumstat_spec: SumStatSpec | None = None,
                 sumstat=None):
        if p < 1:
            raise ValueError("p must be >= 1")
        self.p = float(p)
        self.spec = sumstat_spec
        self._weights_arg = weights
        self._factors_arg = factors
        #: optional Sumstat transform applied to BOTH x and x_0 before the
        #: norm (reference PNormDistance(sumstat=...); PredictorSumstat =
        #: Fearnhead-Prangle learned statistics)
        self.sumstat = sumstat
        #: resolved per-generation weights {t: (S,) array}; -1 = default key
        self.weights: dict[int, np.ndarray] = {}

    # ----------------------------------------------------------- lifecycle
    def initialize(self, t, get_all_sum_stats=None, x_0=None):
        if self.spec is None and isinstance(x_0, Mapping):
            self.spec = SumStatSpec(x_0)
        if self.sumstat is not None:
            self.sumstat.initialize(t, get_all_sum_stats, x_0,
                                    spec=self.spec)
        self._resolve_initial_weights()

    def _transform(self, flat: np.ndarray) -> np.ndarray:
        return flat if self.sumstat is None else self.sumstat(flat)

    def _feature_dim(self) -> int:
        S = self.spec.total_size if self.spec is not None else 0
        return self.sumstat.out_dim(S) if self.sumstat is not None else S

    def _resolve_initial_weights(self):
        w = self._weights_arg
        if w is None:
            return
        if isinstance(w, Mapping) and all(
            isinstance(k, (int, np.integer)) for k in w
        ):
            for t, wt in w.items():
                self.weights[int(t)] = self._coerce_weight_vector(wt)
        else:
            self.weights[-1] = self._coerce_weight_vector(w)

    def _coerce_weight_vector(self, w) -> np.ndarray:
        if isinstance(w, Mapping):
            if self.spec is None:
                raise ValueError(
                    "label-keyed weights require a SumStatSpec"
                )
            vec = np.ones(self.spec.total_size)
            labels = self.spec.labels()
            for k, v in w.items():
                if k in labels:
                    vec[labels.index(k)] = v
                elif k in self.spec.names:
                    off = self.spec.offsets[k]
                    vec[off : off + self.spec.sizes[k]] = v
                else:
                    raise KeyError(f"unknown sum-stat label {k!r}")
            return vec
        return np.ravel(np.asarray(w, np.float64))

    def weights_for(self, t: int | None) -> np.ndarray | None:
        """The weight vector in effect at generation t (latest <= t, else default)."""
        if not self.weights:
            return None
        if t is not None:
            past = [s for s in self.weights if s >= 0 and s <= t]
            if past:
                return self.weights[max(past)]
        return self.weights.get(-1)

    # ----------------------------------------------------------- lifecycle
    def update(self, t, get_all_sum_stats=None, population=None) -> bool:
        """Refit the learned summary transform, if any (base PNorm has no
        adaptive weights; AdaptivePNormDistance extends this)."""
        if self.sumstat is None:
            return False
        return self.sumstat.update(t, population)

    # --------------------------------------------------------------- call
    def __call__(self, x, x_0, t=None, par=None) -> float:
        xf = self._transform(_as_flat(x, self.spec))
        x0f = self._transform(_as_flat(x_0, self.spec))
        w = self.weights_for(t)
        if w is None:
            w = np.ones_like(x0f)
        elif w.shape != x0f.shape:
            if self.sumstat is not None:
                # a refit transform legitimately changes the feature dim;
                # weights fitted for an older space no longer apply
                w = np.ones_like(x0f)
            else:
                try:
                    # scalar / length-1 weights broadcast fine (and always
                    # did); only a genuine length mismatch is user error
                    np.broadcast_shapes(w.shape, x0f.shape)
                except ValueError:
                    raise ValueError(
                        f"weight vector shape {w.shape} does not match the "
                        f"sum-stat vector shape {x0f.shape}"
                    ) from None
        f = self._factors_arg
        if f is not None:
            w = w * self._coerce_weight_vector(f)
        diff = w * np.abs(xf - x0f)
        if np.isinf(self.p):
            return float(np.max(diff))
        return float(np.sum(diff**self.p) ** (1.0 / self.p))

    def host_batch(self, ss_mat: np.ndarray, x0_flat: np.ndarray,
                   t=None) -> np.ndarray | None:
        """Vectorized host twin of :meth:`__call__` over an (n, S) flat
        sum-stat matrix — one numpy expression instead of n Python calls
        (the calibration set is ~1000 rows; the scalar loop was a
        measurable share of a warm benchmark run). Returns None when a
        learned sumstat transform makes per-row evaluation non-trivial;
        callers then fall back to the scalar loop."""
        if self.sumstat is not None:
            return None
        ss_mat = np.asarray(ss_mat, np.float64)
        x0f = np.asarray(x0_flat, np.float64)
        w = self.weights_for(t)
        if w is None:
            w = np.ones_like(x0f)
        f = self._factors_arg
        if f is not None:
            w = w * self._coerce_weight_vector(f)
        diff = w[None, :] * np.abs(ss_mat - x0f[None, :])
        if np.isinf(self.p):
            return np.max(diff, axis=1)
        return np.sum(diff ** self.p, axis=1) ** (1.0 / self.p)

    # ------------------------------------------------------------- device
    def is_device_compatible(self) -> bool:
        if self.sumstat is not None:
            return self.sumstat.is_device_compatible()
        return True

    def device_params(self, t=None):
        if self.spec is None:
            raise RuntimeError("distance not initialized (no SumStatSpec)")
        w = self.weights_for(t)
        if w is None:
            w = np.ones(self._feature_dim())
        f = self._factors_arg
        if f is not None:
            w = w * self._coerce_weight_vector(f)
        w = jnp.asarray(w, jnp.float32)
        if self.sumstat is None:
            return w
        return {"w": w, "ss": self.sumstat.device_params(t)}

    def device_fn(self, spec: SumStatSpec):
        p = self.p
        sumstat = self.sumstat
        ss_fn = sumstat.device_fn(spec) if sumstat is not None else None

        def fn(x, x0, params):
            if ss_fn is not None:
                weights = params["w"]
                x = ss_fn(x, params["ss"])
                x0 = ss_fn(x0, params["ss"])
            else:
                weights = params
            diff = weights * jnp.abs(x - x0)
            if np.isinf(p):
                return jnp.max(diff)
            return jnp.sum(diff**p) ** (1.0 / p)

        return fn

    #: relative slack on the early-reject comparison: the engine's
    #: running prefix sum and the full-vector ``jnp.sum`` may round in
    #: different orders, so a bound within this band of the threshold
    #: never retires (a false keep costs wasted segments; a false retire
    #: would be unsound). 1e-4 is ~200x the f32 summation error of a
    #: 10^4-entry sum and invisible against any real epsilon margin.
    BOUND_RTOL = 1e-4

    def device_bound_fn(self, spec: SumStatSpec):
        """Monotone lower-bound accumulator over sum-stat prefixes.

        For the weighted p-norm ``(sum_i (w_i |x_i - x0_i|)^p)^(1/p)``
        every term is non-negative, so the p-th-power partial sum over
        any index subset lower-bounds the full sum and is non-decreasing
        as entries fold in — the textbook-sound prefix bound. ``p=inf``
        accumulates the running max. Weights come from the SAME
        per-generation ``device_params`` the accept test uses, so a
        weight schedule reweights the bound and the final test together.

        Learned sumstat transforms mix entries across the prefix, so
        the plain partial p-sum is unavailable. For a FITTED LINEAR
        transform at p = 2 the prefix still determines a sound lower
        bound through null-space projectors of the remaining segments'
        coefficient rows (``ops/fit.py::linear_bound_prepare`` holds
        the math); that bound dict carries a ``"prepare"`` hook the
        segmented engine calls once per generation with the live
        distance params and the static emission map. Other learned
        configurations return None (no sound per-prefix bound).
        """
        if self.sumstat is not None:
            return self._transformed_bound_fn()
        p = self.p
        rtol = self.BOUND_RTOL

        def init():
            return jnp.zeros((), jnp.float32)

        if np.isinf(p):
            def step(acc, vals, idx, x0, params):
                diff = params[idx] * jnp.abs(vals - x0[idx])
                return jnp.maximum(acc, jnp.max(diff))

            def exceeds(acc, threshold, params):
                return acc > threshold * (1.0 + rtol)
        else:
            def step(acc, vals, idx, x0, params):
                diff = params[idx] * jnp.abs(vals - x0[idx])
                return acc + jnp.sum(diff ** p)

            def exceeds(acc, threshold, params):
                # compare in the p-th-power domain: acc is the partial
                # p-sum, the accept test is d = total**(1/p) <= thr
                return acc > (threshold * (1.0 + rtol)) ** p

        return {"init": init, "step": step, "exceeds": exceeds}

    def _transformed_bound_fn(self):
        """The projector-based prefix bound for a fitted linear learned
        transform at p = 2 (see :meth:`device_bound_fn`), or None when
        the transform/config has no sound per-prefix bound. Restricted
        to plain ``PNormDistance``: the adaptive variant refits weights
        per generation from a scale reduction that itself needs the
        transformed rows — a circularity the host path resolves."""
        if type(self) is not PNormDistance or self.p != 2.0:
            return None
        from ..predictor.predictor import LinearPredictor
        from ..sumstat.base import PredictorSumstat

        ss = self.sumstat
        if not isinstance(ss, PredictorSumstat):
            return None
        if not isinstance(ss.predictor, LinearPredictor):
            # covers MLP/GP/ModelSelection: nonlinear (or host-only)
            # transforms have no per-prefix linear structure to project
            return None
        if not ss.predictor.fitted or ss._out_dim is None:
            return None
        from ..ops.fit import linear_bound_fns, linear_bound_prepare

        fns = linear_bound_fns(self.BOUND_RTOL, int(ss._out_dim))
        return {**fns, "prepare": linear_bound_prepare}

    def _sumstat_config(self):
        """Identity of the learned-transform stack for kernel-cache
        keying: predictor type + scalar hyperparameters + the fitted
        feature dim. Without this, a distance with and without a learned
        transform (or with differently configured predictors) would hash
        to the SAME compiled-kernel cache key."""
        ss = self.sumstat
        if ss is None:
            return None
        cfg = {"name": type(ss).__name__}
        pred = getattr(ss, "predictor", None)
        if pred is not None:
            pcfg = {"name": type(pred).__name__}
            for attr in ("alpha", "lr", "n_steps", "hidden", "n_iter"):
                val = getattr(pred, attr, None)
                if isinstance(val, (int, float)):
                    pcfg[attr] = val
                elif isinstance(val, (tuple, list)):
                    pcfg[attr] = tuple(val)
            pcfg["fitted"] = bool(pred.fitted)
            cfg["predictor"] = pcfg
        out_dim = getattr(ss, "_out_dim", None)
        if out_dim is not None:
            cfg["out_dim"] = int(out_dim)
        fit_every = getattr(ss, "fit_every", None)
        if fit_every is not None:
            cfg["fit_every"] = int(fit_every)
        return cfg

    def get_config(self):
        cfg = {"name": type(self).__name__, "p": self.p}
        ss_cfg = self._sumstat_config()
        if ss_cfg is not None:
            cfg["sumstat"] = ss_cfg
        return cfg

    def __repr__(self):
        return f"{type(self).__name__}(p={self.p})"


class AdaptivePNormDistance(PNormDistance):
    """Self-reweighting p-norm (pyabc AdaptivePNormDistance).

    Each generation, per-statistic weights are refit to 1/scale over all
    recorded simulations (accepted + rejected) so every summary statistic
    contributes comparably regardless of its magnitude.
    """

    def __init__(self, p: float = 2.0,
                 scale_function: Callable = median_absolute_deviation,
                 adaptive: bool = True,
                 normalize_weights: bool = True,
                 max_weight_ratio: float | None = None,
                 scale_log_file: str | None = None,
                 sumstat_spec: SumStatSpec | None = None,
                 sumstat=None):
        super().__init__(p=p, weights=None, sumstat_spec=sumstat_spec,
                         sumstat=sumstat)
        self.scale_function = scale_function
        self.adaptive = adaptive
        self.normalize_weights = normalize_weights
        self.max_weight_ratio = max_weight_ratio
        self.scale_log_file = scale_log_file
        self._x_0: np.ndarray | None = None

    def requires_calibration(self) -> bool:
        return True

    def configure_sampler(self, sampler):
        """Adaptive reweighting needs rejected simulations too (reference:
        sampler.sample_factory.record_rejected = True)."""
        if self.adaptive:
            sampler.sample_factory.record_rejected = True
        if self.sumstat is not None:
            self.sumstat.configure_sampler(sampler)

    def initialize(self, t, get_all_sum_stats=None, x_0=None):
        super().initialize(t, get_all_sum_stats, x_0)
        self._x_0 = _as_flat(x_0, self.spec) if x_0 is not None else None
        self._x0_dev = None
        if get_all_sum_stats is not None:
            self._fit(t, get_all_sum_stats())

    def update(self, t, get_all_sum_stats=None, population=None) -> bool:
        changed = False
        if self.sumstat is not None:
            # refit the learned statistics first: the scale weights below
            # must live in the NEW transformed feature space
            changed = self.sumstat.update(t, population)
        if not self.adaptive or get_all_sum_stats is None:
            return changed
        self._fit(t, get_all_sum_stats())
        return True

    def device_record_reduce(self, spec=None):
        """Scale reduction traced INTO the generation kernel (the (S,) scale
        ships with the kernel's main fetch; see Distance.device_record_reduce)."""
        if self.sumstat is not None or not self.adaptive:
            return None
        return self.device_scale_impl()

    def device_scale_impl(self):
        """The raw traceable scale twin ``fn(stats (n,S), valid (n,), x0)
        -> (S,)`` for this distance's scale function, or None if the scale
        function has no device twin. Unlike :meth:`device_record_reduce`
        this ignores the sumstat transform — the multigen kernel composes
        it with the sumstat's own device_fn when one is active."""
        if not self.adaptive:
            return None
        from .scale import SCALE_FUNCTIONS, _device_scale_impls

        name = getattr(self.scale_function, "__name__", "")
        # identity check: a custom scale fn shadowing a builtin NAME must
        # run on the host, not be silently replaced by the builtin twin
        if SCALE_FUNCTIONS.get(name) is not self.scale_function:
            return None
        return _device_scale_impls().get(name)

    def _sharded_scale_name(self) -> str | None:
        """The validated builtin scale-function name (identity-checked
        like :meth:`device_scale_impl` — a custom function shadowing a
        builtin name must stay on the host), or None."""
        from .scale import SCALE_FUNCTIONS

        name = getattr(self.scale_function, "__name__", "")
        if SCALE_FUNCTIONS.get(name) is not self.scale_function:
            return None
        return name

    def sharded_scale_capable(self) -> bool:
        """True when the adaptive scale refit is expressible over the
        fixed per-shard moment block — the condition for the SHARDED
        multigen kernel (median-based and true two-pass scales need the
        full cross-shard ring and ride the GSPMD fallback;
        ``_sharded_incapable_reason`` names the alternatives)."""
        from ..ops.scale_reduce import SHARDED_SCALE_NAMES

        if not self.adaptive or self.sumstat is not None:
            return False
        name = self._sharded_scale_name()
        return name is not None and name in SHARDED_SCALE_NAMES

    def device_sharded_reduce(self, spec=None):
        """Moment-expressed scale reduction for the sharded multigen
        kernel (see Distance.device_sharded_reduce): raw sum-stat
        columns, the kernel's own x0 as the moment center, and the
        validated scale-function name for the replicated finisher."""
        from ..ops.scale_reduce import MOMENT_ROWS

        if not self.sharded_scale_capable():
            return None
        return {
            "cols": None, "x0_cols": None,
            "name": self._sharded_scale_name(),
            "moment_rows": MOMENT_ROWS,
            "cols_dim": (spec.total_size if spec is not None else None),
        }

    def device_sharded_dfeat(self, spec):
        """In-lane distance features for the SHARDED kernel's
        recompute-under-new-weights step: ``row(ss, x0) -> (S,)`` stores
        ``|x - x0|^p`` per statistic in the reservoir at accept time, and
        ``combine(feat, w) -> scalar`` evaluates the weighted norm
        ``(sum w^p feat)^(1/p)`` after the new weights exist. Factorizing
        this way keeps the post-generation recompute off the sum-stat
        rows — re-running the full distance on them makes XLA
        re-materialize the simulation chain differently between the
        vmapped virtual-shard and per-device programs (a measured
        ULP-level bit-identity break); the declared fp deviation is that
        ``(sum (w a)^p)^(1/p)`` becomes ``(sum w^p a^p)^(1/p)``."""
        p = self.p

        if np.isinf(p):
            def row(ss, x0):
                return jnp.abs(ss - x0)

            def combine(feat, w):
                return jnp.max(w * feat)
        else:
            def row(ss, x0):
                return jnp.abs(ss - x0) ** p

            def combine(feat, w):
                return jnp.sum((w ** p) * feat) ** (1.0 / p)

        return {"row": row, "combine": combine,
                "dim": spec.total_size}

    def device_weight_update(self):
        """Traceable scale -> weight post-processing for the multi-generation
        device run: ``fn(scale (S,)) -> (S,)`` mirroring :meth:`_fit`
        (1/scale, optional max_weight_ratio clip, mean-1 normalization)."""
        max_ratio = self.max_weight_ratio
        normalize = self.normalize_weights

        def fn(scale):
            w = jnp.where(scale > 0, 1.0 / jnp.where(scale > 0, scale, 1.0),
                          0.0)
            if max_ratio is not None:
                wmin = jnp.min(jnp.where(w > 0, w, jnp.inf))
                w = jnp.minimum(w, wmin * max_ratio)
            if normalize:
                s = w.sum()
                w = jnp.where(s > 0, w * (w.size / jnp.where(s > 0, s, 1.0)),
                              w)
            return w

        return fn

    def _device_scale(self, records) -> np.ndarray | None:
        """Scale vector from the ON-DEVICE record ring without fetching it.

        Preferred source: the reduction already folded into the generation
        kernel (``records.scale``, zero extra syncs). Fallback: a separate
        jitted reduce on the ring (one extra sync — still far cheaper than
        shipping the ring over a TPU tunnel). None when no device twin
        applies (learned sumstat transform, custom scale fn)."""
        if self.sumstat is not None:
            return None
        from .scale import SCALE_FUNCTIONS, device_scale_fn

        name = getattr(self.scale_function, "__name__", "")
        if SCALE_FUNCTIONS.get(name) is not self.scale_function:
            return None  # custom fn shadowing a builtin name: host path
        if records.scale is not None:
            return np.asarray(records.scale, np.float64)
        fn = device_scale_fn(name)
        if fn is None or records.valid_dev is None:
            return None
        import jax
        import jax.numpy as jnp

        if getattr(self, "_x0_dev", None) is None:
            self._x0_dev = jnp.asarray(
                self._x_0 if self._x_0 is not None
                else np.zeros(records.sumstats_dev.shape[1]),
                jnp.float32,
            )
        out = fn(records.sumstats_dev, records.valid_dev, self._x0_dev)
        host = np.asarray(jax.device_get(out), np.float64)
        # this tiny reduced-scale fetch still pays the tunnel floor: count
        # it through the record ring's ledger into syncs_per_run
        records.sync_ledger.record("scale_fetch", host.nbytes)
        return host

    def _fit(self, t: int, samples) -> None:
        """weights[t] = 1/scale over the sample matrix (n, S), computed in
        the (possibly learned) transformed feature space. ``samples`` may be
        a host (n, S) matrix or an on-device ``DeviceRecords`` ring."""
        from ..sampler.base import DeviceRecords

        scale = None
        if isinstance(samples, DeviceRecords):
            scale = self._device_scale(samples)
        if scale is None:
            samples = self._transform(np.asarray(samples, np.float64))
            x0t = (
                self._transform(self._x_0) if self._x_0 is not None else None
            )
            try:
                scale = self.scale_function(samples, x0t)
            except TypeError:
                scale = self.scale_function(samples)
        scale = np.asarray(scale, np.float64)
        w = np.zeros_like(scale)
        pos = scale > 0
        w[pos] = 1.0 / scale[pos]
        if self.max_weight_ratio is not None and pos.any():
            wmin = w[pos].min()
            w = np.minimum(w, wmin * self.max_weight_ratio)
        if self.normalize_weights and w.sum() > 0:
            w = w * (w.size / w.sum())
        self.weights[int(t)] = w
        if self.scale_log_file:
            labels = self.spec.labels() if self.spec else None
            if labels is None or len(labels) != w.size:
                # transformed feature space: positional labels
                labels = [str(i) for i in range(w.size)]
            try:
                with open(self.scale_log_file) as fh:
                    log = json.load(fh)
            except (OSError, json.JSONDecodeError):
                log = {}
            log[str(t)] = dict(zip(labels, w.tolist()))
            with open(self.scale_log_file, "w") as fh:
                json.dump(log, fh, indent=1)

    def get_config(self):
        cfg = {
            "name": type(self).__name__,
            "p": self.p,
            "scale_function": self.scale_function.__name__,
        }
        ss_cfg = self._sumstat_config()
        if ss_cfg is not None:
            cfg["sumstat"] = ss_cfg
        return cfg

    def __repr__(self):
        return (
            f"AdaptivePNormDistance(p={self.p}, "
            f"scale_function={self.scale_function.__name__})"
        )
