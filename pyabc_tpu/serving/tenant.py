"""Tenant identity, lifecycle and per-tenant observability namespace.

A TENANT is one admitted ABC-SMC run living inside the shared serving
process: a declarative :class:`TenantSpec` (what to run), plus the
runtime :class:`Tenant` record the :class:`~pyabc_tpu.serving.scheduler.
RunScheduler` supervises — state machine, attempt counter, run lease,
private History database/checkpoint paths, and a PRIVATE
tracer/metrics pair registered with
:func:`pyabc_tpu.observability.observability_snapshot` so concurrent
runs aggregate side by side instead of interleaving through process
globals (the pre-round-14 one-run-per-process assumption).

Fault-domain contract: everything a tenant's run does — device chunks,
History persists, health recovery — happens on its orchestrator thread
inside ``fault_scope(tenant_id)`` with its own RunSupervisor budget and
its own sticky-isolated writer handle, so an injected kill, hang or
NaN-poison against tenant A is invisible to tenant B except through OS
scheduling. The chaos tests in ``tests/test_serving.py`` hold that
line.

This module deliberately does NOT construct :class:`ABCSMC` or touch a
device context — abc-lint ISO001 reserves that for the scheduler's
leased path; :meth:`TenantSpec.abcsmc_kwargs` only DESCRIBES the run.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field

from ..observability import FlightRecorder, MetricsRegistry, Tracer

# ------------------------------------------------------------ tenant states
#: admitted, waiting for a device slot
QUEUED = "queued"
#: holding a device slot; orchestrator thread live under a run lease
RUNNING = "running"
#: lease reaped (thread dead or hung); waiting to restart from checkpoint
REQUEUED = "requeued"
#: finished with a posterior
COMPLETED = "completed"
#: terminal failure (requeue budget exhausted / degenerate / error)
FAILED = "failed"
#: cancelled by the client before completion
CANCELLED = "cancelled"
#: gracefully drained on SIGTERM: History flushed + final checkpoint
DRAINED = "drained"

#: states the scheduler will never move a tenant out of
TERMINAL_STATES = frozenset({COMPLETED, FAILED, CANCELLED, DRAINED})


def _build_gaussian(spec: "TenantSpec") -> dict:
    """Conjugate-normal toy: cheap fused-path workload (CPU chaos tests
    and the bench `serve` lane run fleets of these)."""
    import pyabc_tpu as pt

    noise_sd = float(spec.params.get("noise_sd", 0.5))

    @pt.JaxModel.from_function(["theta"], name="gauss")
    def model(key, theta):
        import jax

        return {"x": theta[0] + noise_sd * jax.random.normal(key)}

    prior = pt.Distribution(theta=pt.RV("norm", 0.0, 1.0))
    return {
        "models": model,
        "parameter_priors": prior,
        "distance_function": pt.PNormDistance(p=2),
        "eps": pt.MedianEpsilon(),
        "observed": {"x": float(spec.params.get("x_obs", 1.0))},
    }


def _build_lotka_volterra(spec: "TenantSpec") -> dict:
    """The bench's LV ODE config — the production-shaped workload."""
    import pyabc_tpu as pt
    from ..models import lotka_volterra as lv

    return {
        "models": lv.make_lv_model(),
        "parameter_priors": lv.default_prior(),
        "distance_function": pt.AdaptivePNormDistance(p=2),
        "eps": pt.MedianEpsilon(),
        "observed": lv.observed_data(seed=int(spec.data_seed)),
    }


def _build_gillespie_bd(spec: "TenantSpec") -> dict:
    """Stochastic birth-death (tau-leap Gillespie) — the scenario zoo's
    cheap stochastic-kinetics workload, traffic-class ``gillespie``."""
    import pyabc_tpu as pt
    from ..models import gillespie as g

    n_leaps = int(spec.params.get("n_leaps", 100))
    n_obs = int(spec.params.get("n_obs", 10))
    return {
        "models": g.make_birth_death_model(n_leaps=n_leaps, n_obs=n_obs),
        "parameter_priors": g.birth_death_prior(),
        "distance_function": pt.PNormDistance(p=2),
        "eps": pt.MedianEpsilon(),
        "observed": g.observed_birth_death(
            seed=int(spec.data_seed), n_leaps=n_leaps, n_obs=n_obs),
    }


def _build_sir(spec: "TenantSpec") -> dict:
    """Deterministic SIR ODE with observation noise (noisy-ABC)."""
    import pyabc_tpu as pt
    from ..models import sir

    n_obs = int(spec.params.get("n_obs", 15))
    return {
        "models": sir.make_sir_model(n_obs=n_obs),
        "parameter_priors": sir.default_prior(),
        "distance_function": pt.PNormDistance(p=2),
        "eps": pt.MedianEpsilon(),
        "observed": sir.observed_data(
            seed=int(spec.data_seed), n_obs=n_obs),
    }


def _build_selection_pair(spec: "TenantSpec") -> dict:
    """K=2 tractable model selection (analytic model posterior) — the
    K>1 fused-kernel path exercised at traffic scale."""
    import pyabc_tpu as pt
    from ..models import model_selection as msel

    models, priors, _ = msel.tractable_pair()
    return {
        "models": models,
        "parameter_priors": priors,
        "distance_function": pt.PNormDistance(p=2),
        "eps": pt.MedianEpsilon(),
        "observed": {"x": float(spec.params.get("x_obs", 0.7))},
    }


#: declarative model registry the submit API draws from; each builder
#: maps a spec to ABCSMC component kwargs + the observed data
MODEL_BUILDERS = {
    "gaussian": _build_gaussian,
    "lotka_volterra": _build_lotka_volterra,
    "gillespie_bd": _build_gillespie_bd,
    "sir": _build_sir,
    "selection_pair": _build_selection_pair,
}


@dataclass
class TenantSpec:
    """What to run — declarative and JSON-serializable (the submit API
    posts exactly these fields).

    ``model`` names a :data:`MODEL_BUILDERS` entry; ``params`` feeds the
    builder (observation value, noise scale, ...); ``abcsmc_overrides``
    passes through to the ABCSMC constructor (checkpointing, health
    floors) — the scheduler supplies tracer/metrics/checkpoint_path
    itself and rejects overrides colliding with them.
    """

    model: str = "gaussian"
    population_size: int = 100
    generations: int = 4
    seed: int = 0
    fused_generations: int = 4
    data_seed: int = 123
    #: sharded fused sampling (mesh-aware serving): run this many shards
    #: (a power of two >= 2), mapped to a CONTIGUOUS SUB-MESH lease by
    #: the scheduler. The reduction is a pure function of the shard
    #: count, so placement may grant ANY divisor width — 4 shards on 4
    #: chips, 2 chips, or virtually on 1 — and a preempted/requeued
    #: tenant resumes bit-identical on whatever width is free next.
    #: None = unsharded (a width-1 slot, packable per chip).
    sharded: int | None = None
    #: opt-in to sub-mesh leases WIDER than one host's device segment
    #: (round 18 fleets): a multi-host lease puts DCN collectives in the
    #: tenant's critical path and dies whenever ANY of its hosts does,
    #: so straddling hosts is never implicit. False = the scheduler only
    #: tries host-confined divisor widths (the kernel contract makes
    #: them bit-identical anyway).
    multi_host: bool = False
    #: per-particle sumstat retention. Default True: lease-expiry
    #: REQUEUE resumes via History `load()`, whose adaptive-state
    #: restore reads the last stored generation's sum stats — a tenant
    #: that opts out trades the smaller fetch/db for failing its
    #: requeue (first attempts are unaffected)
    store_sum_stats: bool | int = True
    #: History backend for this tenant's private db: "rows" (reference
    #: SQL layout) or "columnar" (round 17 — SQL metadata + one Parquet
    #: record batch per generation, written straight from the packed
    #: fetch; needs the optional pyarrow). The scheduler encodes the
    #: choice in the tenant's db URL scheme, so requeue-resume and the
    #: parity helpers re-open it self-describingly.
    store: str = "rows"
    minimum_epsilon: float | None = None
    max_walltime_s: float | None = None
    params: dict = field(default_factory=dict)
    abcsmc_overrides: dict = field(default_factory=dict)

    #: constructor kwargs the scheduler owns; a spec override colliding
    #: with one of these is an admission-time validation error
    RESERVED_OVERRIDES = frozenset({
        "tracer", "metrics", "checkpoint_path", "seed",
        "population_size", "fused_generations", "mesh", "sharded",
    })

    def validate(self) -> None:
        if self.model not in MODEL_BUILDERS:
            raise ValueError(
                f"unknown model {self.model!r} "
                f"(one of {sorted(MODEL_BUILDERS)})"
            )
        if int(self.population_size) < 2:
            raise ValueError("population_size must be >= 2")
        if int(self.generations) < 1:
            raise ValueError("generations must be >= 1")
        if int(self.fused_generations) < 1:
            raise ValueError("fused_generations must be >= 1")
        if self.store not in ("rows", "columnar"):
            raise ValueError(
                f"store must be 'rows' or 'columnar', got {self.store!r}")
        if self.store == "columnar":
            from ..storage.columnar import has_pyarrow

            if not has_pyarrow():
                raise ValueError(
                    "store='columnar' needs the optional 'pyarrow' "
                    "package on the serving host (pip install pyarrow); "
                    "submit with store='rows' instead")
        if self.sharded is not None:
            n = int(self.sharded)
            if n < 2 or n & (n - 1):
                raise ValueError(
                    "sharded must be a power of two >= 2 (or None for "
                    "an unsharded width-1 tenant)")
        if self.multi_host and not self.sharded:
            raise ValueError(
                "multi_host=True needs sharded=<n>: only a sharded "
                "sub-mesh lease can span host segments")
        bad = self.RESERVED_OVERRIDES & set(self.abcsmc_overrides)
        if bad:
            raise ValueError(
                f"abcsmc_overrides may not set {sorted(bad)}: the "
                f"scheduler owns those (per-tenant namespace/lease path)"
            )

    def abcsmc_kwargs(self) -> dict:
        """ABCSMC constructor kwargs + the observed data for this spec.

        Returns ``{"kwargs": {...}, "observed": {...}}``; the SCHEDULER
        turns this into a live run inside its leased path (ISO001).
        """
        built = MODEL_BUILDERS[self.model](self)
        observed = built.pop("observed")
        kwargs = dict(built)
        kwargs.update(
            population_size=int(self.population_size),
            seed=int(self.seed),
            fused_generations=int(self.fused_generations),
        )
        kwargs.update(self.abcsmc_overrides)
        return {"kwargs": kwargs, "observed": observed}

    def to_dict(self) -> dict:
        return {
            "model": self.model,
            "population_size": int(self.population_size),
            "generations": int(self.generations),
            "seed": int(self.seed),
            "fused_generations": int(self.fused_generations),
            "data_seed": int(self.data_seed),
            "sharded": (None if self.sharded is None
                        else int(self.sharded)),
            "multi_host": bool(self.multi_host),
            "store_sum_stats": self.store_sum_stats,
            "store": self.store,
            "minimum_epsilon": self.minimum_epsilon,
            "max_walltime_s": self.max_walltime_s,
            "params": dict(self.params),
            "abcsmc_overrides": {
                k: v for k, v in self.abcsmc_overrides.items()
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "TenantSpec":
        known = {f for f in cls.__dataclass_fields__}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown TenantSpec fields {sorted(unknown)}")
        return cls(**d)


class Tenant:
    """One admitted run's supervised runtime record.

    Owned and mutated by the scheduler (under its lock for state
    transitions); exposes read-only JSON-ready views for the API and a
    ``namespace_snapshot`` for the process observability snapshot. The
    private ``tracer``/``metrics`` pair IS the tenant's observability
    namespace — the scheduler passes them into the tenant's ABCSMC so
    every span/gauge of the run lands here, not in process globals.
    """

    def __init__(self, tenant_id: str, spec: TenantSpec, *, clock,
                 db_path: str, checkpoint_path: str,
                 flight_path: str | None = None,
                 max_events: int = 512):
        self.id = str(tenant_id)
        self.spec = spec
        self.clock = clock
        self.db_path = str(db_path)
        self.checkpoint_path = str(checkpoint_path)
        self.state = QUEUED
        self.attempt = 0
        self.requeues = 0
        self.abc_id: int | None = None
        self.submitted_at = clock.now()
        self.started_at: float | None = None
        self.finished_at: float | None = None
        #: wall seconds actually spent RUNNING (summed over attempts)
        self.run_s = 0.0
        #: chip-seconds actually consumed (run seconds × lease width,
        #: summed over attempts) — the quota-accounting unit
        self.chip_s = 0.0
        #: last lifecycle measurement of this tenant's on-disk bytes
        #: (db + WAL + columnar files + archive + checkpoint)
        self.bytes_on_disk = 0
        #: quota-remaining view ({chip_seconds, bytes_on_disk,
        #: generations}; None until the lifecycle layer first computes it)
        self.quota_remaining: dict | None = None
        #: lifecycle disposal happened: the History files were deleted
        #: (or archived) — status survives, the data artifacts are gone
        self.disposed = False
        self.generations_done = 0
        self.error: str | None = None
        #: the PR-6 health trail of a failed run, shipped with status
        self.health_trail: list[dict] = []
        self.kernel_cache_hit: bool | None = None
        self.cancel_requested = False
        #: checkpoint-preemption: the scheduler asked this tenant to
        #: stop at its next chunk boundary and REQUEUE (drain
        #: fragmentation / admit a latency-sensitive small tenant);
        #: unlike cancel/drain the stop is not terminal
        self.preempt_requested = False
        self.preemptions = 0
        #: scheduler bookkeeping: when the preempt was requested (span
        #: start) / since when the queued tenant has been unplaceable
        #: (auto-preemption trigger)
        self._preempt_t0: float | None = None
        self._unplaced_since: float | None = None
        self._device_loss_t0: float | None = None
        #: requeues caused by the sub-mesh losing a device — an
        #: infrastructure fault, so NOT charged against max_requeues
        self.device_loss_requeues = 0
        #: current sub-mesh lease (None while queued) + the width
        #: history, one entry per started attempt: the device-loss and
        #: preemption contracts assert re-placement on a DIFFERENT width
        self.submesh_lo: int | None = None
        self.submesh_width: int | None = None
        self.widths: list[int] = []
        self.result: dict | None = None
        #: live run handle (the scheduler's leased ABCSMC); None unless
        #: RUNNING — drain and cancel reach the run through it
        self.abc = None
        #: current orchestrator thread (one per attempt)
        self.thread: threading.Thread | None = None
        #: epoch guard: results reported by a STALE attempt (a hung
        #: thread waking after its lease was reaped) are discarded
        self.epoch = 0
        self._lock = threading.Lock()
        self._events: list[dict] = []  # abc-lint: guarded-by=_lock
        self._event_seq = 0  # abc-lint: guarded-by=_lock
        self._event_waiters = threading.Condition(self._lock)
        self._max_events = int(max_events)
        #: per-tenant observability namespace (its own injected-clock
        #: tracer + registry; the run, History writer and supervisor all
        #: record here)
        self.tracer = Tracer(clock=clock)
        self.metrics = MetricsRegistry(clock=clock)
        #: the crash-safe black box (ISSUE 19): armed against THIS
        #: tenant's private namespace, so a fault-path dump carries the
        #: span tail, metric deltas since admission, and the lifecycle
        #: event ring — persisted to ``flight_path`` by the scheduler's
        #: fault hooks and served live via /api/tenant/<id>/flight
        self.flight_path = (str(flight_path) if flight_path is not None
                            else None)
        self.flight = FlightRecorder(
            self.id, clock=clock, path=self.flight_path)
        self.flight.arm(tracer=self.tracer, metrics=self.metrics,
                        events_fn=self.events_snapshot)

    # ----------------------------------------------------------- events
    def record_event(self, kind: str, **attrs) -> None:
        """Append one lifecycle/progress event (bounded ring; the
        streaming API tails it)."""
        with self._lock:
            self._event_seq += 1
            self._events.append({
                "seq": self._event_seq, "kind": kind,
                "ts": round(self.clock.now(), 6), **attrs,
            })
            if len(self._events) > self._max_events:
                del self._events[: len(self._events) - self._max_events]
            self._event_waiters.notify_all()

    def events_snapshot(self) -> list[dict]:
        """The whole current event ring (the flight recorder's source)."""
        with self._lock:
            return [dict(e) for e in self._events]

    def events_since(self, seq: int, timeout_s: float = 0.0) -> list[dict]:
        """Events with ``seq > seq`` (optionally waiting up to
        ``timeout_s`` for the first new one) — the stream API's tail."""
        deadline = self.clock.now() + max(float(timeout_s), 0.0)
        with self._lock:
            while True:
                out = [e for e in self._events if e["seq"] > seq]
                if out or timeout_s <= 0:
                    return out
                remaining = deadline - self.clock.now()
                if remaining <= 0:
                    return out
                self._event_waiters.wait(timeout=min(remaining, 0.25))

    # ------------------------------------------------------------ views
    def to_status(self) -> dict:
        """JSON-ready status for the API / scheduler snapshot."""
        with self._lock:
            n_events = self._event_seq
        return {
            "id": self.id,
            "state": self.state,
            "model": self.spec.model,
            "population_size": int(self.spec.population_size),
            "generations": int(self.spec.generations),
            "generations_done": int(self.generations_done),
            "seed": int(self.spec.seed),
            "attempt": int(self.attempt),
            "requeues": int(self.requeues),
            "sharded": (None if self.spec.sharded is None
                        else int(self.spec.sharded)),
            "submesh": (
                None if self.submesh_width is None else {
                    "lo": self.submesh_lo,
                    "width": self.submesh_width,
                }
            ),
            "widths": list(self.widths),
            "preemptions": int(self.preemptions),
            "device_loss_requeues": int(self.device_loss_requeues),
            "submitted_at": round(self.submitted_at, 6),
            "started_at": self.started_at,
            "finished_at": self.finished_at,
            "run_s": round(self.run_s, 6),
            "chip_s": round(self.chip_s, 6),
            "bytes_on_disk": int(self.bytes_on_disk),
            "quota_remaining": self.quota_remaining,
            "disposed": bool(self.disposed),
            "db": self.db_path,
            "checkpoint": self.checkpoint_path,
            "flight": self.flight_path,
            "flight_dumps": int(self.flight.n_dumps),
            "kernel_cache_hit": self.kernel_cache_hit,
            "error": self.error,
            "health_trail": list(self.health_trail),
            "result": self.result,
            "n_events": n_events,
        }

    def namespace_snapshot(self) -> dict:
        """This tenant's slice of ``observability_snapshot()`` —
        private tracer + metrics, never interleaved with another run's."""
        return {
            "state": self.state,
            "tracer": self.tracer.snapshot(),
            "metrics": self.metrics.snapshot(),
        }

    def compile_span_count(self) -> int:
        """Dispatch spans of this tenant that PAID a kernel trace/compile
        (`compile=True`); 0 for a shape-keyed kernel-cache hit — the
        serving tests assert exactly that."""
        return sum(
            1 for sp in self.tracer.spans()
            if sp.name == "dispatch" and sp.attrs.get("compile")
        )

    def __repr__(self):
        return f"Tenant({self.id!r}, state={self.state!r})"
