"""RunScheduler — admit, queue and supervise concurrent ABC-SMC runs.

The serving layer's core (round 14): one process, ``n_slots`` device
slots, MANY tenants. Every live tenant is a LEASED run — the slot
handout reuses :class:`~pyabc_tpu.resilience.lease.LeaseTable`
semantics verbatim (one slot per tenant, deadlines on the injected
clock, any orchestrator heartbeat refreshes): an orchestrator thread
that dies hard (injected kill — no report, no goodbye) or hangs past
the lease timeout is PRESUMED DEAD, its device slot is reclaimed, and
the tenant is requeued to resume from its PR-5 checkpoint — or failed
with its PR-6 health trail once the requeue budget is spent. Survivor
tenants never notice; that containment is chaos-tested on CPU.

Fault domains: each tenant's run gets its own orchestrator thread under
``fault_scope(tenant_id)`` (a process-global FaultPlan rule with
``match=<tenant>`` fires only inside that tenant), its own RunSupervisor
budget (per-run by construction), its own History database on the
shared :class:`~pyabc_tpu.storage.WriterPool` (sticky persist failures
latch per handle), and its own tracer/metrics namespace (registered
with ``observability_snapshot()`` — concurrent runs aggregate, never
interleave).

Zero-compile admission: a shape-keyed
:class:`~pyabc_tpu.utils.xla_cache.KernelCache` adopts the compiled
``DeviceContext`` of any previously-served identical program shape into
the new tenant, so tenant k+1 with a seen shape pays no trace/compile
at all.

ISO001 (abc-lint): this module is the ONLY place in
``pyabc_tpu/serving/`` allowed to construct :class:`ABCSMC` or touch a
device context — runs exist solely inside the scheduler's leased path.
"""
from __future__ import annotations

import itertools
import os
import threading
from collections import deque

from ..observability import (
    SYSTEM_CLOCK,
    global_metrics,
    register_tenant_source,
    unregister_tenant_source,
)
from ..observability.metrics import (
    TENANT_COMPLETED_TOTAL,
    TENANT_DRAINS_TOTAL,
    TENANT_FAILURES_TOTAL,
    TENANT_KERNEL_CACHE_HITS_TOTAL,
    TENANT_KERNEL_CACHE_MISSES_TOTAL,
    TENANT_REQUEUES_TOTAL,
    TENANTS_LIVE_GAUGE,
    TENANTS_QUEUED_GAUGE,
)
from ..resilience.lease import LeaseTable
from ..storage import WriterPool
from ..utils.xla_cache import KernelCache
from .admission import AdmissionController, AdmissionRejectedError
from .tenant import (
    CANCELLED,
    COMPLETED,
    DRAINED,
    FAILED,
    QUEUED,
    REQUEUED,
    RUNNING,
    TERMINAL_STATES,
    Tenant,
    TenantSpec,
)


class RunScheduler:
    """Admits, queues and supervises tenants over shared device slots."""

    #: default run-lease timeout. Sized ABOVE the worst silent stretch
    #: of a HEALTHY fresh-shape tenant: the fused program's 15-25 s XLA
    #: compile happens inside ``abc.run()``, between the "db open"
    #: heartbeat and the first chunk event — a timeout inside that
    #: window falsely reaps every kernel-cache-missing tenant
    #: mid-compile (and its requeued retry recompiles and is reaped
    #: again). Dead threads are detected immediately regardless; this
    #: only bounds HANG detection.
    DEFAULT_LEASE_TIMEOUT_S = 60.0

    def __init__(self, n_slots: int = 1, *, max_queued: int = 16,
                 lease_timeout_s: float = DEFAULT_LEASE_TIMEOUT_S,
                 max_requeues: int = 1,
                 base_dir: str | None = None, clock=None, metrics=None,
                 writer_threads: int = 2, kernel_cache_entries: int = 8,
                 tick_s: float = 0.05, max_terminal_tenants: int = 256):
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self.metrics = metrics if metrics is not None else global_metrics()
        self.n_slots = max(int(n_slots), 1)
        self.max_requeues = int(max_requeues)
        self.tick_s = float(tick_s)
        #: terminal tenants retained for status queries; beyond this the
        #: oldest-finished are evicted (records, event rings, private
        #: tracer/metrics namespaces) so a long-lived serving process
        #: does not grow without bound
        self.max_terminal_tenants = max(int(max_terminal_tenants), 1)
        if base_dir is None:
            import tempfile

            base_dir = tempfile.mkdtemp(prefix="abc-serve-")
        self.base_dir = str(base_dir)
        os.makedirs(self.base_dir, exist_ok=True)

        self.admission = AdmissionController(
            max_queued=max_queued, n_slots=self.n_slots, clock=self.clock,
            metrics=self.metrics,
        )
        #: run-level leases: slot index leased to tenant id; heartbeats
        #: come from the tenant's per-chunk callback
        self.leases = LeaseTable(self.clock, timeout_s=lease_timeout_s)
        self.kernel_cache = KernelCache(max_entries=kernel_cache_entries)
        self.writer_pool = WriterPool(n_threads=writer_threads)

        self._lock = threading.RLock()
        self._wake = threading.Condition(self._lock)
        self._tenants: dict[str, Tenant] = {}  # abc-lint: guarded-by=_lock
        self._queue: deque = deque()  # abc-lint: guarded-by=_lock
        self._free_slots: list[int] = list(range(self.n_slots))  # abc-lint: guarded-by=_lock
        self._slot_of: dict[str, int] = {}  # abc-lint: guarded-by=_lock
        self._reports: deque = deque()  # abc-lint: guarded-by=_lock
        #: terminal tenant ids, oldest-finished first (eviction order)
        self._terminal_order: deque = deque()  # abc-lint: guarded-by=_lock
        self._ids = itertools.count(1)
        self._draining = False
        self._shutdown = False
        self.stale_reports_discarded = 0
        self._pump = threading.Thread(
            target=self._pump_loop, daemon=True, name="abc-serve-pump")
        self._pump.start()

    # ------------------------------------------------------------- submit
    def submit(self, spec: TenantSpec, tenant_id: str | None = None
               ) -> Tenant:
        """Admit a run (or raise :class:`AdmissionRejectedError`).

        Returns the supervised :class:`Tenant` immediately; the run
        starts when a device slot frees up."""
        with self._lock:
            if self._shutdown or self._draining:
                raise AdmissionRejectedError(
                    "scheduler is draining: not admitting new tenants",
                    retry_after_s=None,
                )
            queued_now = len(self._queue)
            live_now = len(self._slot_of)
            self.admission.admit(
                spec, queued_now=queued_now, live_now=live_now)
            tid = (str(tenant_id) if tenant_id is not None
                   else f"tenant-{next(self._ids)}")
            if tid in self._tenants:
                raise AdmissionRejectedError(
                    f"tenant id {tid!r} already exists", retry_after_s=None)
            tenant = Tenant(
                tid, spec, clock=self.clock,
                db_path=f"sqlite:///{self.base_dir}/{tid}.db",
                checkpoint_path=os.path.join(self.base_dir, f"{tid}.ck"),
            )
            self._tenants[tid] = tenant
            self._queue.append(tid)
            register_tenant_source(tid, tenant)
            tenant.record_event("admitted", queued_ahead=queued_now)
            self._set_occupancy_gauges_locked()
            self._wake.notify_all()
        return tenant

    def get(self, tenant_id: str) -> Tenant | None:
        with self._lock:
            return self._tenants.get(str(tenant_id))

    def cancel(self, tenant_id: str) -> bool:
        """Cancel a queued tenant immediately; ask a running one to stop
        gracefully (it flushes + checkpoints, then lands CANCELLED).
        Returns False for unknown/terminal tenants."""
        with self._lock:
            tenant = self._tenants.get(str(tenant_id))
            if tenant is None or tenant.state in TERMINAL_STATES:
                return False
            tenant.cancel_requested = True
            if tenant.state in (QUEUED, REQUEUED):
                self._dequeue_locked(tenant.id)
                self._finish_locked(tenant, CANCELLED,
                                    error="cancelled before start")
                return True
            if tenant.state == RUNNING:
                # tenant.abc may still be None (attempt thread mid-
                # build): the flag set above is re-checked by the
                # attempt right after it assigns tenant.abc
                if tenant.abc is not None:
                    tenant.abc.request_graceful_stop()
                tenant.record_event("cancel_requested")
            return True

    # -------------------------------------------------------------- drain
    def drain(self, timeout_s: float = 60.0) -> dict:
        """Graceful SIGTERM path: stop admitting, cancel queued tenants,
        ask every RUNNING tenant to stop (each flushes its History and
        writes a final checkpoint via the PR-6 GracefulShutdown path),
        and wait for them. Returns a summary; tenants still running at
        the deadline are reported ``forced`` (their leases will reap)."""
        with self._lock:
            self._draining = True
            for tid in list(self._queue):
                tenant = self._tenants[tid]
                self._dequeue_locked(tid)
                self._finish_locked(tenant, CANCELLED,
                                    error="drained before start")
            running = [t for t in self._tenants.values()
                       if t.state == RUNNING]
            for tenant in running:
                if tenant.abc is not None:
                    tenant.abc.request_graceful_stop()
                tenant.record_event("drain_requested")
            self._wake.notify_all()
        # the wait deadline rides SYSTEM_CLOCK, not the injected clock:
        # a manually-stepped test clock never advances on its own, and
        # a hung RUNNING tenant would spin this loop forever instead of
        # timing out and landing in `forced`. The injected clock keeps
        # its job for lease/timestamp bookkeeping only.
        deadline = SYSTEM_CLOCK.now() + float(timeout_s)
        with self._lock:
            while any(t.state == RUNNING for t in self._tenants.values()):
                remaining = deadline - SYSTEM_CLOCK.now()
                if remaining <= 0:
                    break
                self._wake.wait(timeout=min(remaining, self.tick_s))
        with self._lock:
            states = {t.id: t.state for t in self._tenants.values()}
            forced = [tid for tid, st in states.items() if st == RUNNING]
        return {"states": states, "forced": forced}

    def shutdown(self) -> None:
        """Stop the pump and the writer pool (drain first for grace)."""
        with self._lock:
            self._shutdown = True
            self._wake.notify_all()
        self._pump.join(timeout=10)
        self.writer_pool.close()

    # ----------------------------------------------------------- snapshot
    def snapshot(self) -> dict:
        with self._lock:
            tenants = [t.to_status() for t in self._tenants.values()]
            queue = list(self._queue)
            free = len(self._free_slots)
        return {
            "n_slots": self.n_slots,
            "free_slots": free,
            "queue": queue,
            "draining": self._draining,
            "tenants": tenants,
            "leases": self.leases.stats(),
            "admission": self.admission.stats(),
            "kernel_cache": self.kernel_cache.stats(),
            "stale_reports_discarded": int(self.stale_reports_discarded),
        }

    # ------------------------------------------------------------ pump
    def _pump_loop(self) -> None:
        while True:
            # _wake shares _lock; acquiring the lock is what wait() needs
            with self._lock:
                if self._shutdown:
                    return
                self._drain_reports_locked()
                self._reap_leases_locked()
                self._start_queued_locked()
                self._set_occupancy_gauges_locked()
                self._wake.wait(timeout=self.tick_s)

    def _drain_reports_locked(self) -> None:
        while self._reports:
            tid, epoch, outcome, payload = self._reports.popleft()
            tenant = self._tenants.get(tid)
            if tenant is None:
                continue
            if epoch != tenant.epoch or tenant.state != RUNNING:
                # a STALE attempt (lease already reaped, tenant moved
                # on) woke up and reported: exactly-once means its
                # outcome is discarded, loudly
                self.stale_reports_discarded += 1
                tenant.record_event("stale_report_discarded",
                                    outcome=outcome, epoch=epoch)
                continue
            self._release_slot_locked(tenant)
            run_s = payload.get("run_s", 0.0)
            tenant.run_s += run_s
            self.admission.note_run_seconds(run_s)
            if outcome == COMPLETED:
                tenant.result = payload.get("result")
                self._finish_locked(tenant, COMPLETED)
            elif outcome == DRAINED:
                state = (CANCELLED
                         if getattr(tenant, "cancel_requested", False)
                         else DRAINED)
                self._finish_locked(tenant, state,
                                    error=payload.get("error"))
            else:  # failed
                tenant.health_trail = payload.get("trail") or []
                self._finish_locked(tenant, FAILED,
                                    error=payload.get("error"))

    def _reap_leases_locked(self) -> None:
        # hard-dead orchestrator threads (injected kill: no report, no
        # goodbye) are presumed dead immediately — same contract as the
        # broker's worker liveness window, just cheaper to detect
        dead = [
            t.id for t in self._tenants.values()
            if t.state == RUNNING and t.thread is not None
            and not t.thread.is_alive()
        ]
        events = self.leases.reap(self.clock.now(), dead_wids=dead)
        # run-level leases requeue the TENANT, never the slot range:
        # nothing ever pops the table's requeue deque, so discard the
        # ranges the reap just pushed or they accumulate forever
        self.leases.discard_requeued()
        for ev in events:
            tenant = self._tenants.get(ev["wid"])
            if tenant is None or tenant.state != RUNNING:
                continue
            tenant.record_event("lease_reaped", reason=ev["reason"])
            # stale-ify the attempt: a hung thread waking later reports
            # into a bumped epoch and is discarded; ask it to stop at
            # its next chunk so it cannot keep burning the device
            tenant.epoch += 1
            if tenant.abc is not None:
                tenant.abc.request_graceful_stop()
            self._release_slot_locked(tenant, lease_already_gone=True)
            if self._draining:
                self._finish_locked(
                    tenant, FAILED,
                    error=f"lease {ev['reason']} during drain")
            elif tenant.requeues >= self.max_requeues:
                self._finish_locked(
                    tenant, FAILED,
                    error=(f"requeue budget exhausted after lease "
                           f"{ev['reason']} "
                           f"({tenant.requeues}/{self.max_requeues})"))
            else:
                tenant.requeues += 1
                tenant.state = REQUEUED
                tenant.abc = None
                self._queue.append(tenant.id)
                tenant.record_event("requeued", attempt=tenant.attempt)
                self.metrics.counter(
                    TENANT_REQUEUES_TOTAL,
                    "run leases reaped with the tenant requeued from "
                    "its checkpoint",
                ).inc()

    def _start_queued_locked(self) -> None:
        i = 0
        while self._free_slots and i < len(self._queue):
            tid = self._queue[i]
            tenant = self._tenants[tid]
            # a requeued tenant must not race its own stale thread on
            # the db/checkpoint: wait for that thread to exit first (the
            # slot stays free for OTHER tenants meanwhile — no head-of-
            # line blocking: we skip, not stall)
            if tenant.thread is not None and tenant.thread.is_alive():
                i += 1
                continue
            del self._queue[i]
            slot = self._free_slots.pop(0)
            self._slot_of[tid] = slot
            self.leases.grant(tid, slot, slot + 1)
            tenant.state = RUNNING
            tenant.attempt += 1
            epoch = tenant.epoch
            if tenant.started_at is None:
                tenant.started_at = self.clock.now()
            tenant.record_event("started", slot=slot,
                                attempt=tenant.attempt)
            tenant.thread = threading.Thread(
                target=self._run_tenant_attempt,
                args=(tenant, epoch),
                daemon=True, name=f"abc-serve-{tid}-a{tenant.attempt}",
            )
            tenant.thread.start()

    def _release_slot_locked(self, tenant: Tenant,
                             lease_already_gone: bool = False) -> None:
        slot = self._slot_of.pop(tenant.id, None)
        if slot is None:
            return
        if not lease_already_gone:
            self.leases.note_delivery(slot)
        self._free_slots.append(slot)

    def _dequeue_locked(self, tid: str) -> None:
        try:
            self._queue.remove(tid)
        except ValueError:
            pass

    def _finish_locked(self, tenant: Tenant, state: str,
                       error: str | None = None) -> None:
        tenant.state = state
        tenant.error = error
        tenant.finished_at = self.clock.now()
        tenant.abc = None
        tenant.record_event(state, error=error)
        counters = {
            COMPLETED: (TENANT_COMPLETED_TOTAL,
                        "tenants finished with a posterior"),
            FAILED: (TENANT_FAILURES_TOTAL,
                     "tenants failed terminally"),
            DRAINED: (TENANT_DRAINS_TOTAL,
                      "tenants drained gracefully (flush + final "
                      "checkpoint)"),
        }
        if state in counters:
            name, help_ = counters[state]
            self.metrics.counter(name, help_).inc()
        self._evict_terminal_locked(tenant.id)
        self._set_occupancy_gauges_locked()
        self._wake.notify_all()

    def _evict_terminal_locked(self, tid: str) -> None:
        """Bound terminal-tenant retention: keep the newest
        ``max_terminal_tenants`` finished records for status queries,
        evict the oldest beyond that (tenant record, event ring,
        observability namespace) — a long-lived serving process must
        not grow with every tenant it has ever finished."""
        self._terminal_order.append(tid)
        while len(self._terminal_order) > self.max_terminal_tenants:
            old_tid = self._terminal_order.popleft()
            old = self._tenants.get(old_tid)
            if old is None:
                continue
            if old.state not in TERMINAL_STATES:  # resurrection guard
                continue
            # a stale attempt thread may still be unwinding; it holds
            # its own reference to the Tenant object and reports into a
            # bumped epoch, so dropping the registry entry is safe
            del self._tenants[old_tid]
            unregister_tenant_source(old_tid)

    def _set_occupancy_gauges_locked(self) -> None:
        self.metrics.gauge(
            TENANTS_LIVE_GAUGE,
            "tenants currently holding a device slot",
        ).set(len(self._slot_of))
        self.metrics.gauge(
            TENANTS_QUEUED_GAUGE,
            "tenants admitted and waiting for a device slot",
        ).set(len(self._queue))

    # ------------------------------------------- the leased run (ISO001)
    def _heartbeat(self, tenant: Tenant, epoch: int) -> None:
        """Refresh the tenant's run lease — orchestrator-thread progress
        only (setup milestones + per-chunk events); a hung thread makes
        no progress and its lease expires. NOTE the timeout contract:
        ``lease_timeout_s`` must exceed the worst silent stretch of a
        healthy run (one chunk's compute plus, on a kernel-cache miss,
        the XLA compile) — a DEAD thread is detected immediately via
        thread liveness, the timeout only bounds HANG detection."""
        with self._lock:
            if epoch != tenant.epoch:
                return  # stale attempt: no heartbeat rights
            self.leases.touch_worker(tenant.id)

    def _on_chunk(self, tenant: Tenant, epoch: int, ev: dict) -> None:
        """Per-chunk heartbeat from the tenant's orchestrator thread:
        refresh the run lease, advance progress, feed the event stream."""
        self._heartbeat(tenant, epoch)
        with self._lock:
            if epoch != tenant.epoch:
                return
            # re-assert an acknowledged stop (idempotent): a cancel or
            # drain that raced run() entry — which clears any pre-run
            # stop request — would otherwise be lost and the run would
            # land COMPLETED despite the ack
            run = (tenant.abc
                   if tenant.cancel_requested or self._draining
                   else None)
        if run is not None:
            run.request_graceful_stop()
        done = int(ev.get("t_first", 0)) + int(ev.get("gens", 0))
        tenant.generations_done = max(tenant.generations_done, done)
        tenant.record_event(
            "chunk", t_first=ev.get("t_first"), gens=ev.get("gens"),
            n_acc=ev.get("n_acc"), chunk_s=round(ev.get("chunk_s", 0.0), 6),
        )

    def _report(self, tenant: Tenant, epoch: int, outcome: str,
                **payload) -> None:
        with self._lock:
            self._reports.append((tenant.id, epoch, outcome, payload))
            self._wake.notify_all()

    def _run_tenant_attempt(self, tenant: Tenant, epoch: int) -> None:
        """One leased attempt, on its own orchestrator thread — the only
        place in pyabc_tpu/serving/ that builds an ABCSMC (ISO001)."""
        from ..inference.smc import ABCSMC, GracefulShutdown
        from ..resilience.faults import InjectedKill, fault_scope
        from ..resilience.health import DegenerateRunError

        t_run0 = self.clock.now()
        with fault_scope(tenant.id):
            try:
                built = tenant.spec.abcsmc_kwargs()
                abc = ABCSMC(
                    tracer=tenant.tracer, metrics=tenant.metrics,
                    checkpoint_path=tenant.checkpoint_path,
                    **built["kwargs"],
                )
                self._heartbeat(tenant, epoch)  # setup milestone: built
                if tenant.abc_id is not None:
                    # requeued attempt: resume this tenant's run — the
                    # PR-5 checkpoint adoption inside run() restores the
                    # mid-chunk carry bit-exact
                    abc.load(tenant.db_path, tenant.abc_id)
                else:
                    abc.new(tenant.db_path, built["observed"],
                            store_sum_stats=tenant.spec.store_sum_stats)
                    tenant.abc_id = int(abc.history.id)
                # per-tenant History stream on the SHARED writer pool,
                # tagged with this tenant's fault domain
                abc.history.writer_pool = self.writer_pool
                abc.history.writer_scope = tenant.id
                self._heartbeat(tenant, epoch)  # setup milestone: db open
                hit = self.kernel_cache.adopt_or_register(abc)
                if tenant.kernel_cache_hit is None:
                    tenant.kernel_cache_hit = hit
                self.metrics.counter(
                    TENANT_KERNEL_CACHE_HITS_TOTAL if hit
                    else TENANT_KERNEL_CACHE_MISSES_TOTAL,
                    "shape-keyed kernel cache hits (zero compile)"
                    if hit else "shape-keyed kernel cache misses",
                ).inc()
                tenant.record_event("kernel_cache",
                                    hit=hit, attempt=tenant.attempt)
                with self._lock:
                    tenant.abc = abc
                    # a cancel (or drain) acknowledged while tenant.abc
                    # was still None set the flag with no run handle to
                    # stop — honor it here or the run proceeds to
                    # COMPLETED despite the ack. Skipping run() (rather
                    # than request_graceful_stop) because run() clears
                    # any pre-run stop request at entry.
                    stop_now = (epoch == tenant.epoch
                                and (tenant.cancel_requested
                                     or self._draining))
                if stop_now:
                    self._report(
                        tenant, epoch, DRAINED,
                        error="stopped before run start",
                        run_s=self.clock.now() - t_run0,
                    )
                    return
                abc.chunk_event_cb = (
                    lambda ev, _t=tenant, _e=epoch:
                    self._on_chunk(_t, _e, ev)
                )
                run_kwargs: dict = {
                    "max_nr_populations": int(tenant.spec.generations),
                }
                if tenant.spec.minimum_epsilon is not None:
                    run_kwargs["minimum_epsilon"] = float(
                        tenant.spec.minimum_epsilon)
                if tenant.spec.max_walltime_s is not None:
                    run_kwargs["max_walltime"] = float(
                        tenant.spec.max_walltime_s)
                h = abc.run(**run_kwargs)
                self.kernel_cache.register_from(abc)
                result = {
                    "n_populations": int(h.n_populations),
                    "total_simulations": int(h.total_nr_simulations),
                }
                self._report(
                    tenant, epoch, COMPLETED, result=result,
                    run_s=self.clock.now() - t_run0,
                )
            except InjectedKill:
                # HARD death: no report, no slot release — the run
                # lease must expire (or the dead thread be noticed) for
                # the scheduler to reclaim and requeue; this is the
                # chaos tests' primary weapon
                return
            except GracefulShutdown as exc:
                self._report(
                    tenant, epoch, DRAINED, error=str(exc),
                    run_s=self.clock.now() - t_run0,
                )
            except DegenerateRunError as exc:
                self._report(
                    tenant, epoch, FAILED,
                    error=f"degenerate run: {exc}", trail=exc.trail,
                    run_s=self.clock.now() - t_run0,
                )
            except BaseException as exc:  # noqa: BLE001 - tenant fault
                # domain boundary: ANY other orchestrator failure is
                # contained to this tenant and reported typed
                self._report(
                    tenant, epoch, FAILED, error=repr(exc)[:500],
                    run_s=self.clock.now() - t_run0,
                )
