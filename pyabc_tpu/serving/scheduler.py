"""RunScheduler — admit, queue and supervise concurrent ABC-SMC runs.

The serving layer's core (round 14, made topology-aware in round 15):
one process, a DEVICE POOL, MANY tenants. Every live tenant is a LEASED
run — the handout reuses :class:`~pyabc_tpu.resilience.lease.LeaseTable`
semantics verbatim (deadlines on the injected clock, any orchestrator
heartbeat refreshes): an orchestrator thread that dies hard (injected
kill — no report, no goodbye) or hangs past the lease timeout is
PRESUMED DEAD, its sub-mesh is reclaimed, and the tenant is requeued to
resume from its PR-5 checkpoint — or failed with its PR-6 health trail
once the requeue budget is spent. Survivor tenants never notice; that
containment is chaos-tested on CPU.

Mesh-aware serving (round 15) — three additions on the same spine:

- SUB-MESH LEASES: a slot is a contiguous sub-mesh of 1/2/4/8 devices
  from :class:`~pyabc_tpu.serving.placement.SubMeshAllocator` (buddy
  allocation, coalescing on free, width-1 packing). A ``sharded=n``
  tenant is granted the WIDEST free power-of-two divisor of ``n`` —
  the PR-9/15 kernel contract makes the reduction a pure function of
  the shard count, so any width is bit-identical, down to virtual
  shards on one device. Admission prices backpressure in chip-seconds
  over the healthy pool, not queue position.
- CHECKPOINT-PREEMPTION: :meth:`preempt` (or the auto policy when a
  queued tenant sits unplaceable past ``preempt_queue_wait_s``) asks a
  big tenant to stop at its next chunk boundary via the PR-10 graceful
  path; instead of landing DRAINED it REQUEUES with its checkpoint and
  resumes — bit-identical by construction — on whatever sub-mesh is
  free next. Preemption drains fragmentation and admits latency-
  sensitive small tenants without ever losing a big tenant's work.
- DEVICE-LOSS SURVIVAL: a ``device_lost`` event (the polled
  ``device.mesh`` fault site, or :meth:`mark_devices_lost` from real
  monitoring) marks devices dead: every lease touching them is reaped,
  the allocator quarantines the devices (capacity shrinks, admission
  reprices), and the affected tenants requeue WITHOUT consuming their
  requeue budget — an infrastructure fault is not the tenant's fault —
  to resume on a different-width sub-mesh. Losing half the mesh
  degrades throughput, never correctness.

Fault domains: each tenant's run gets its own orchestrator thread under
``fault_scope(tenant_id)`` (a process-global FaultPlan rule with
``match=<tenant>`` fires only inside that tenant), its own RunSupervisor
budget (per-run by construction), its own History database on the
shared :class:`~pyabc_tpu.storage.WriterPool` (sticky persist failures
latch per handle), and its own tracer/metrics namespace (registered
with ``observability_snapshot()`` — concurrent runs aggregate, never
interleave).

Zero-compile admission: a shape-keyed
:class:`~pyabc_tpu.utils.xla_cache.KernelCache` adopts the compiled
``DeviceContext`` of any previously-served identical program shape into
the new tenant, so tenant k+1 with a seen shape pays no trace/compile
at all.

ISO001 (abc-lint): this module is the ONLY place in
``pyabc_tpu/serving/`` allowed to construct :class:`ABCSMC` or touch a
device context — runs exist solely inside the scheduler's leased path.
"""
from __future__ import annotations

import itertools
import os
import threading
from collections import deque

from ..observability import (
    SYSTEM_CLOCK,
    SloEngine,
    global_metrics,
    register_tenant_source,
    unregister_tenant_source,
)
from ..observability.metrics import (
    ADMISSION_LATENCY_HISTOGRAM,
    DEVICES_LOST_TOTAL,
    FLIGHT_DUMPS_TOTAL,
    HOSTS_LOST_TOTAL,
    SUBMESH_DEVICES_FREE_GAUGE,
    SUBMESH_DEVICES_HEALTHY_GAUGE,
    SUBMESH_WIDEST_FREE_GAUGE,
    TENANT_COMPLETED_TOTAL,
    TENANT_DEVICE_LOSS_REQUEUES_TOTAL,
    TENANT_DRAINS_TOTAL,
    TENANT_FAILURES_TOTAL,
    TENANT_KERNEL_CACHE_HITS_TOTAL,
    TENANT_KERNEL_CACHE_MISSES_TOTAL,
    TENANT_PREEMPTIONS_TOTAL,
    TENANT_REQUEUES_TOTAL,
    TENANTS_LIVE_GAUGE,
    TENANTS_QUEUED_GAUGE,
)
from ..observability.metrics import TIME_TO_POSTERIOR_HISTOGRAM
from ..resilience.lease import LeaseTable
from ..storage import WriterPool
from ..utils.xla_cache import KernelCache
from . import placement
from .admission import AdmissionController, AdmissionRejectedError
from .lifecycle import LifecycleManager, RetentionPolicy, TenantQuota
from .tenant import (
    CANCELLED,
    COMPLETED,
    DRAINED,
    FAILED,
    QUEUED,
    REQUEUED,
    RUNNING,
    TERMINAL_STATES,
    Tenant,
    TenantSpec,
)


class RunScheduler:
    """Admits, queues and supervises tenants over shared device slots."""

    #: default run-lease timeout. Sized ABOVE the worst silent stretch
    #: of a HEALTHY fresh-shape tenant: the fused program's 15-25 s XLA
    #: compile happens inside ``abc.run()``, between the "db open"
    #: heartbeat and the first chunk event — a timeout inside that
    #: window falsely reaps every kernel-cache-missing tenant
    #: mid-compile (and its requeued retry recompiles and is reaped
    #: again). Dead threads are detected immediately regardless; this
    #: only bounds HANG detection.
    DEFAULT_LEASE_TIMEOUT_S = 60.0

    def __init__(self, n_slots: int = 1, *, n_devices: int | None = None,
                 packing: int = 1, n_hosts: int = 1, max_queued: int = 16,
                 lease_timeout_s: float = DEFAULT_LEASE_TIMEOUT_S,
                 max_requeues: int = 1,
                 preempt_queue_wait_s: float | None = None,
                 base_dir: str | None = None, clock=None, metrics=None,
                 writer_threads: int = 2, kernel_cache_entries: int = 8,
                 tick_s: float = 0.05, max_terminal_tenants: int = 256,
                 retention: RetentionPolicy | None = None,
                 quota: TenantQuota | None = None,
                 lifecycle_sweep_s: float = 5.0,
                 slos=None, slo_sample_interval_s: float = 10.0):
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self.metrics = metrics if metrics is not None else global_metrics()
        #: the device pool the allocator manages. ``n_devices`` sizes it
        #: explicitly (pass ``placement.platform_device_count()`` to
        #: serve the real platform); the legacy ``n_slots`` shorthand
        #: sizes a pool of that many width-1-equivalent devices —
        #: single-device deployments keep their exact pre-round-15
        #: concurrency semantics. Leases beyond the PHYSICAL device
        #: count (or width 1) run their shards virtually — bit-identical
        #: by the kernel's width-independence contract.
        pool = int(n_devices) if n_devices is not None else max(
            int(n_slots), 1)
        #: ``n_hosts > 1`` models the fleet: the pool splits into equal
        #: per-host device segments, leases confine to one host unless
        #: the tenant explicitly opted into multi-host placement, and a
        #: dead host reaps its whole segment in one step
        self.allocator = placement.SubMeshAllocator(
            pool, packing=max(int(packing), 1),
            n_hosts=max(int(n_hosts), 1))
        self.n_hosts = self.allocator.n_hosts
        self.packing = self.allocator.packing
        #: width-1-equivalent concurrency, kept for API/status compat
        self.n_slots = pool * self.packing
        self.max_requeues = int(max_requeues)
        #: auto-preemption: a queued tenant unplaceable for this long
        #: triggers a checkpoint-preemption of the widest running tenant
        #: (None = only explicit ``preempt()`` calls preempt)
        self.preempt_queue_wait_s = (
            None if preempt_queue_wait_s is None
            else float(preempt_queue_wait_s))
        self.tick_s = float(tick_s)
        #: terminal tenants retained for status queries; beyond this the
        #: oldest-finished are evicted (records, event rings, private
        #: tracer/metrics namespaces) so a long-lived serving process
        #: does not grow without bound
        self.max_terminal_tenants = max(int(max_terminal_tenants), 1)
        if base_dir is None:
            import tempfile

            base_dir = tempfile.mkdtemp(prefix="abc-serve-")
        self.base_dir = str(base_dir)
        os.makedirs(self.base_dir, exist_ok=True)

        self.admission = AdmissionController(
            max_queued=max_queued, n_chips=pool, clock=self.clock,
            metrics=self.metrics, n_hosts=self.n_hosts,
        )
        #: retention/GC/quota layer (round 19): the pump sweeps it,
        #: submit consults its quota gate, and terminal-tenant eviction
        #: routes file disposal through it — bounded disk for a
        #: long-lived serving process
        self.lifecycle = LifecycleManager(
            policy=retention, quota=quota, clock=self.clock,
            metrics=self.metrics, sweep_interval_s=lifecycle_sweep_s,
        )
        #: run-level leases: synthetic unique slot ids leased per tenant
        #: (device RANGES live in the allocator; packed width-1 tenants
        #: share devices, so lease slots must not collide); heartbeats
        #: come from the tenant's per-chunk callback
        self.leases = LeaseTable(self.clock, timeout_s=lease_timeout_s)
        self.kernel_cache = KernelCache(max_entries=kernel_cache_entries)
        self.writer_pool = WriterPool(n_threads=writer_threads)
        #: SLO burn-rate engine (round 22): samples the scheduler's own
        #: instruments on the pump cadence, so live burn state is one
        #: ``snapshot()["slo"]`` away and the gauges export for free
        self.slo = SloEngine(
            self.metrics, slos=slos, clock=self.clock,
            sample_interval_s=slo_sample_interval_s,
        )

        self._lock = threading.RLock()
        self._wake = threading.Condition(self._lock)
        self._tenants: dict[str, Tenant] = {}  # abc-lint: guarded-by=_lock
        self._queue: deque = deque()  # abc-lint: guarded-by=_lock
        #: tenant id -> synthetic lease slot id of the current attempt
        self._lease_slot_of: dict[str, int] = {}  # abc-lint: guarded-by=_lock
        self._lease_seq = itertools.count()
        self._reports: deque = deque()  # abc-lint: guarded-by=_lock
        #: terminal tenant ids, oldest-finished first (eviction order)
        self._terminal_order: deque = deque()  # abc-lint: guarded-by=_lock
        self._ids = itertools.count(1)
        self._draining = False
        self._shutdown = False
        self.stale_reports_discarded = 0
        self.devices_lost_total = 0
        self.hosts_lost_total = 0
        self._pump = threading.Thread(
            target=self._pump_loop, daemon=True, name="abc-serve-pump")
        self._pump.start()

    # ------------------------------------------------------------- submit
    def submit(self, spec: TenantSpec, tenant_id: str | None = None
               ) -> Tenant:
        """Admit a run (or raise :class:`AdmissionRejectedError`).

        Returns the supervised :class:`Tenant` immediately; the run
        starts when a device slot frees up."""
        t_admit = self.clock.now()
        with self._lock:
            if self._shutdown or self._draining:
                raise AdmissionRejectedError(
                    "scheduler is draining: not admitting new tenants",
                    retry_after_s=None,
                )
            queued_now = len(self._queue)
            live_now = len(self._lease_slot_of)
            self.admission.admit(
                spec, queued_now=queued_now, live_now=live_now)
            # quota gate rides NEXT to the chip-second backpressure: a
            # spec the tenant quota cannot fit is refused non-retryably
            # (retrying the same oversized spec later will not help)
            self.lifecycle.admission_check(spec)
            tid = (str(tenant_id) if tenant_id is not None
                   else f"tenant-{next(self._ids)}")
            if tid in self._tenants:
                raise AdmissionRejectedError(
                    f"tenant id {tid!r} already exists", retry_after_s=None)
            # the URL scheme carries the tenant's store choice, so every
            # re-open (requeue-resume load(), parity helpers, dashboards)
            # picks the right backend without out-of-band state
            scheme = ("sqlite+columnar" if spec.store == "columnar"
                      else "sqlite")
            tenant = Tenant(
                tid, spec, clock=self.clock,
                db_path=f"{scheme}:///{self.base_dir}/{tid}.db",
                checkpoint_path=os.path.join(self.base_dir, f"{tid}.ck"),
                flight_path=os.path.join(self.base_dir, f"{tid}.flight"),
            )
            self._tenants[tid] = tenant
            self._queue.append(tid)
            register_tenant_source(tid, tenant)
            tenant.record_event("admitted", queued_ahead=queued_now)
            self.metrics.histogram(
                ADMISSION_LATENCY_HISTOGRAM,
                "admission decision latency for admitted tenants "
                "(submit entry -> queued), seconds",
            ).observe(max(self.clock.now() - t_admit, 0.0))
            self._set_occupancy_gauges_locked()
            self._wake.notify_all()
        return tenant

    def get(self, tenant_id: str) -> Tenant | None:
        with self._lock:
            return self._tenants.get(str(tenant_id))

    def cancel(self, tenant_id: str) -> bool:
        """Cancel a queued tenant immediately; ask a running one to stop
        gracefully (it flushes + checkpoints, then lands CANCELLED).
        Returns False for unknown/terminal tenants."""
        with self._lock:
            tenant = self._tenants.get(str(tenant_id))
            if tenant is None or tenant.state in TERMINAL_STATES:
                return False
            tenant.cancel_requested = True
            if tenant.state in (QUEUED, REQUEUED):
                self._dequeue_locked(tenant.id)
                self._finish_locked(tenant, CANCELLED,
                                    error="cancelled before start")
                return True
            if tenant.state == RUNNING:
                # tenant.abc may still be None (attempt thread mid-
                # build): the flag set above is re-checked by the
                # attempt right after it assigns tenant.abc
                if tenant.abc is not None:
                    tenant.abc.request_graceful_stop()
                tenant.record_event("cancel_requested")
            return True

    def preempt(self, tenant_id: str) -> bool:
        """Checkpoint-preempt a RUNNING tenant: it stops at its next
        chunk boundary through the graceful path (flush + checkpoint,
        bit-identical by construction), REQUEUES, and resumes on
        whatever sub-mesh is free when its turn comes — possibly a
        different width (the kernel's width-independence contract).
        Frees its sub-mesh to drain fragmentation or admit latency-
        sensitive small tenants. Returns False for tenants not
        currently running."""
        with self._lock:
            tenant = self._tenants.get(str(tenant_id))
            if tenant is None or tenant.state != RUNNING:
                return False
            if tenant.cancel_requested or tenant.preempt_requested:
                return False
            tenant.preempt_requested = True
            tenant._preempt_t0 = self.clock.now()
            if tenant.abc is not None:
                tenant.abc.request_graceful_stop()
            tenant.record_event(
                "preempt_requested",
                width=tenant.submesh_width, lo=tenant.submesh_lo)
            return True

    # -------------------------------------------------------------- drain
    def drain(self, timeout_s: float = 60.0) -> dict:
        """Graceful SIGTERM path: stop admitting, cancel queued tenants,
        ask every RUNNING tenant to stop (each flushes its History and
        writes a final checkpoint via the PR-6 GracefulShutdown path),
        and wait for them. Returns a summary; tenants still running at
        the deadline are reported ``forced`` (their leases will reap)."""
        with self._lock:
            self._draining = True
            for tid in list(self._queue):
                tenant = self._tenants[tid]
                self._dequeue_locked(tid)
                self._finish_locked(tenant, CANCELLED,
                                    error="drained before start")
            running = [t for t in self._tenants.values()
                       if t.state == RUNNING]
            for tenant in running:
                if tenant.abc is not None:
                    tenant.abc.request_graceful_stop()
                tenant.record_event("drain_requested")
            self._wake.notify_all()
        # the wait deadline rides SYSTEM_CLOCK, not the injected clock:
        # a manually-stepped test clock never advances on its own, and
        # a hung RUNNING tenant would spin this loop forever instead of
        # timing out and landing in `forced`. The injected clock keeps
        # its job for lease/timestamp bookkeeping only.
        deadline = SYSTEM_CLOCK.now() + float(timeout_s)
        with self._lock:
            while any(t.state == RUNNING for t in self._tenants.values()):
                remaining = deadline - SYSTEM_CLOCK.now()
                if remaining <= 0:
                    break
                self._wake.wait(timeout=min(remaining, self.tick_s))
        with self._lock:
            states = {t.id: t.state for t in self._tenants.values()}
            forced = [tid for tid, st in states.items() if st == RUNNING]
        return {"states": states, "forced": forced}

    def shutdown(self) -> None:
        """Stop the pump and the writer pool (drain first for grace)."""
        with self._lock:
            self._shutdown = True
            self._wake.notify_all()
        self._pump.join(timeout=10)
        self.writer_pool.close()

    # ----------------------------------------------------------- snapshot
    def snapshot(self, *, state: str | None = None, offset: int = 0,
                 limit: int | None = None) -> dict:
        """Scheduler + tenant status view; the tenant list is optionally
        state-filtered and PAGED (round 19 — listing stays O(page) with
        hundreds of live tenants; the default stays the full list for
        compatibility). Disk/quota accounting is refreshed only for the
        returned page."""
        with self._lock:
            records = list(self._tenants.values())
            if state is not None:
                records = [t for t in records if t.state == str(state)]
            total = len(records)
            off = max(int(offset), 0)
            page = (records[off:off + max(int(limit), 0)]
                    if limit is not None else records[off:])
            for tenant in page:
                self.lifecycle.bytes_on_disk(tenant)
                tenant.quota_remaining = self.lifecycle.quota_remaining(
                    tenant)
            tenants = [t.to_status() for t in page]
            queue = list(self._queue)
            place = self.allocator.stats()
        return {
            "n_slots": self.n_slots,
            "free_slots": place["free_devices"] * self.packing,
            "queue": queue,
            "draining": self._draining,
            "tenants": tenants,
            "tenants_total": total,
            "offset": off,
            "limit": limit,
            "state_filter": state,
            "placement": place,
            "devices_lost_total": int(self.devices_lost_total),
            "hosts_lost_total": int(self.hosts_lost_total),
            "leases": self.leases.stats(),
            "admission": self.admission.stats(),
            "lifecycle": self.lifecycle.stats(),
            "kernel_cache": self.kernel_cache.stats(),
            "stale_reports_discarded": int(self.stale_reports_discarded),
            "slo": self.slo.snapshot(),
        }

    def status(self, tenant_id: str) -> dict | None:
        """One tenant's status with freshly-computed disk/quota fields
        (the per-tenant API route)."""
        with self._lock:
            tenant = self._tenants.get(str(tenant_id))
            if tenant is None:
                return None
            self.lifecycle.bytes_on_disk(tenant)
            tenant.quota_remaining = self.lifecycle.quota_remaining(tenant)
            return tenant.to_status()

    # --------------------------------------------------- device health
    def mark_devices_lost(self, devices) -> list[str]:
        """Hard mesh loss (real monitoring or the injected
        ``device_lost`` fault): quarantine the devices, reap every
        lease touching them, requeue the affected tenants (their
        requeue budget untouched — infrastructure faults are not the
        tenant's fault) and reprice admission on the shrunken pool.
        Returns the affected tenant ids."""
        with self._lock:
            return self._apply_device_loss_locked(devices)

    def mark_devices_degraded(self, devices) -> None:
        """Soft cordon: no NEW placements on these devices; existing
        leases drain naturally."""
        with self._lock:
            self.allocator.mark_degraded(devices)
            self._set_occupancy_gauges_locked()

    def mark_host_lost(self, host: int) -> list[str]:
        """Whole-host loss (a machine died, not a chip): the host's
        entire allocator segment quarantines in one step, every lease
        touching it is reaped, the affected tenants requeue budget-free
        from their chunk-boundary checkpoints, and admission reprices
        on the surviving fleet capacity. Returns the affected tenant
        ids."""
        with self._lock:
            return self._apply_host_loss_locked(host)

    def _apply_device_loss_locked(self, devices) -> list[str]:
        devices = sorted({int(d) for d in devices})
        before = self.allocator.healthy_count()
        affected = self.allocator.mark_lost(devices)
        self._count_devices_lost_locked(before)
        self._requeue_lost_leases_locked(affected, devices, "device_lost")
        return affected

    def _apply_host_loss_locked(self, host: int) -> list[str]:
        host = int(host)
        before = self.allocator.healthy_count()
        hosts_before = self.allocator.hosts_lost_total
        affected = self.allocator.mark_host_lost(host)
        self._count_devices_lost_locked(before)
        n_hosts = self.allocator.hosts_lost_total - hosts_before
        if n_hosts:
            self.hosts_lost_total += n_hosts
            self.metrics.counter(
                HOSTS_LOST_TOTAL,
                "hosts marked lost (whole-segment quarantine: every "
                "lease on the host reaped, fleet capacity repriced)",
            ).inc(n_hosts)
        dph = self.allocator.devices_per_host
        segment = list(range(host * dph, (host + 1) * dph))
        # the dead host's run-level leases are reaped in one step (not
        # timed out): run leases requeue the TENANT, so the slot ranges
        # the reap pushes are discarded like every other reap path
        self.leases.reap_wids(affected, reason="host_lost")
        self.leases.discard_requeued()
        self._requeue_lost_leases_locked(affected, segment, "host_lost",
                                         host=host, lease_already_gone=True)
        return affected

    def _count_devices_lost_locked(self, healthy_before: int) -> None:
        n_lost = healthy_before - self.allocator.healthy_count()
        if n_lost:
            self.devices_lost_total += n_lost
            self.metrics.counter(
                DEVICES_LOST_TOTAL,
                "devices marked lost (mesh loss: capacity shrunk, "
                "leases reaped)",
            ).inc(n_lost)

    def _requeue_lost_leases_locked(self, affected, devices, cause,
                                    host: int | None = None,
                                    lease_already_gone: bool = False
                                    ) -> None:
        """The shared back half of device and host loss: reprice
        admission on the surviving capacity, then reap + budget-free
        requeue every affected RUNNING tenant (infrastructure faults
        are never the tenant's fault)."""
        self.admission.set_capacity(self.allocator.healthy_count())
        t_loss = self.clock.now()
        for tid in affected:
            tenant = self._tenants.get(tid)
            if tenant is None or tenant.state != RUNNING:
                continue
            extra = {} if host is None else {"host": int(host)}
            tenant.record_event(
                cause, devices=devices,
                width=tenant.submesh_width, lo=tenant.submesh_lo,
                **extra)
            tenant.flight.note(cause, devices=devices,
                               width=tenant.submesh_width, **extra)
            tenant._device_loss_t0 = t_loss
            # stale-ify the attempt (a thread still computing on "lost"
            # hardware reports into a bumped epoch and is discarded)
            # and ask it to stop at its next chunk boundary
            tenant.epoch += 1
            if tenant.abc is not None:
                tenant.abc.request_graceful_stop()
            self._release_placement_locked(
                tenant, lease_already_gone=lease_already_gone)
            if self._draining:
                self._finish_locked(
                    tenant, FAILED, error=f"{cause} during drain")
                continue
            tenant.device_loss_requeues += 1
            tenant.state = REQUEUED
            tenant.abc = None
            self._queue.append(tenant.id)
            tenant.record_event("requeued", attempt=tenant.attempt,
                                cause=cause)
            self.metrics.counter(
                TENANT_DEVICE_LOSS_REQUEUES_TOTAL,
                "tenants requeued because their sub-mesh lost a device "
                "(requeue budget untouched)",
            ).inc()
            # flight file covers detection -> reap -> requeue: the dump
            # lands after the "requeued" event so the postmortem
            # timeline shows the full loss window
            self._dump_flight_locked(tenant, reason=cause)
        self._set_occupancy_gauges_locked()
        self._wake.notify_all()

    def _poll_device_faults_locked(self) -> None:
        """The deterministic ``device.mesh`` chaos site: the pump polls
        the active FaultPlan every tick, so mesh loss is injectable on
        CPU exactly like every other fault kind. ``host_lost`` reads
        the fault's device spec as HOST indices."""
        from ..resilience.faults import maybe_device_fault

        ev = maybe_device_fault("device.mesh")
        if ev is None:
            return
        if ev["kind"] == "device_lost":
            self._apply_device_loss_locked(ev["devices"])
        elif ev["kind"] == "host_lost":
            for h in sorted({int(h) for h in ev["devices"]}):
                self._apply_host_loss_locked(h)
        else:  # device_degraded
            self.allocator.mark_degraded(ev["devices"])

    # ------------------------------------------------------------ pump
    def _pump_loop(self) -> None:
        while True:
            # _wake shares _lock; acquiring the lock is what wait() needs
            with self._lock:
                if self._shutdown:
                    return
                self._drain_reports_locked()
                self._poll_device_faults_locked()
                self._reap_leases_locked()
                self._start_queued_locked()
                self._maybe_auto_preempt_locked()
                self._maybe_lifecycle_sweep_locked()
                self._evict_overflow_locked()
                self._set_occupancy_gauges_locked()
                # SLO burn-rate sampling rides the pump tick (self-
                # throttled to its own sample interval); evaluation
                # reads counters/histograms only, never tenant state
                self.slo.sample()
                self._wake.wait(timeout=self.tick_s)

    def _drain_reports_locked(self) -> None:
        while self._reports:
            tid, epoch, outcome, payload = self._reports.popleft()
            tenant = self._tenants.get(tid)
            if tenant is None:
                continue
            if epoch != tenant.epoch or tenant.state != RUNNING:
                # a STALE attempt (lease already reaped, tenant moved
                # on) woke up and reported: exactly-once means its
                # outcome is discarded, loudly
                self.stale_reports_discarded += 1
                tenant.record_event("stale_report_discarded",
                                    outcome=outcome, epoch=epoch)
                continue
            width = tenant.submesh_width or 1
            self._release_placement_locked(tenant)
            run_s = payload.get("run_s", 0.0)
            tenant.run_s += run_s
            # chip-seconds: wall time × the sub-mesh width it held
            tenant.chip_s += run_s * width
            self.admission.note_run_seconds(run_s, chips=width)
            if outcome == COMPLETED:
                tenant.result = payload.get("result")
                self._finish_locked(tenant, COMPLETED)
            elif outcome == DRAINED:
                if (tenant.preempt_requested
                        and not getattr(tenant, "cancel_requested", False)
                        and not self._draining):
                    self._requeue_preempted_locked(tenant)
                else:
                    state = (CANCELLED
                             if getattr(tenant, "cancel_requested", False)
                             else DRAINED)
                    self._finish_locked(tenant, state,
                                        error=payload.get("error"))
            else:  # failed
                tenant.health_trail = payload.get("trail") or []
                self._finish_locked(tenant, FAILED,
                                    error=payload.get("error"))

    def _requeue_preempted_locked(self, tenant: Tenant) -> None:
        """A checkpoint-preempted tenant is NOT terminal: it requeues
        with its checkpoint (requeue budget untouched) and will resume
        on whatever sub-mesh is free when its turn comes."""
        tenant.preempt_requested = False
        tenant.preemptions += 1
        tenant.state = REQUEUED
        tenant.abc = None
        self._queue.append(tenant.id)
        tenant.record_event("preempted", attempt=tenant.attempt)
        if tenant._preempt_t0 is not None:
            tenant.tracer.record_span(
                "preempt.drain", tenant._preempt_t0, self.clock.now(),
                thread="scheduler",
            )
            tenant._preempt_t0 = None
        self.metrics.counter(
            TENANT_PREEMPTIONS_TOTAL,
            "tenants checkpoint-preempted at a chunk boundary and "
            "requeued",
        ).inc()
        self._wake.notify_all()

    def _reap_leases_locked(self) -> None:
        # hard-dead orchestrator threads (injected kill: no report, no
        # goodbye) are presumed dead immediately — same contract as the
        # broker's worker liveness window, just cheaper to detect
        dead = [
            t.id for t in self._tenants.values()
            if t.state == RUNNING and t.thread is not None
            and not t.thread.is_alive()
        ]
        events = self.leases.reap(self.clock.now(), dead_wids=dead)
        # run-level leases requeue the TENANT, never the slot range:
        # nothing ever pops the table's requeue deque, so discard the
        # ranges the reap just pushed or they accumulate forever
        self.leases.discard_requeued()
        for ev in events:
            tenant = self._tenants.get(ev["wid"])
            if tenant is None or tenant.state != RUNNING:
                continue
            tenant.record_event("lease_reaped", reason=ev["reason"])
            tenant.flight.note("lease_reaped", reason=ev["reason"],
                               epoch=tenant.epoch)
            # stale-ify the attempt: a hung thread waking later reports
            # into a bumped epoch and is discarded; ask it to stop at
            # its next chunk so it cannot keep burning the device
            tenant.epoch += 1
            if tenant.abc is not None:
                tenant.abc.request_graceful_stop()
            self._release_placement_locked(tenant,
                                           lease_already_gone=True)
            if self._draining:
                self._finish_locked(
                    tenant, FAILED,
                    error=f"lease {ev['reason']} during drain")
            elif tenant.requeues >= self.max_requeues:
                self._finish_locked(
                    tenant, FAILED,
                    error=(f"requeue budget exhausted after lease "
                           f"{ev['reason']} "
                           f"({tenant.requeues}/{self.max_requeues})"))
            else:
                tenant.requeues += 1
                tenant.state = REQUEUED
                tenant.abc = None
                self._queue.append(tenant.id)
                tenant.record_event("requeued", attempt=tenant.attempt)
                self.metrics.counter(
                    TENANT_REQUEUES_TOTAL,
                    "run leases reaped with the tenant requeued from "
                    "its checkpoint",
                ).inc()
                # dump AFTER the requeue decision so the flight file's
                # timeline covers detection -> reap -> requeue (the
                # FAILED branches dump through _finish_locked)
                self._dump_flight_locked(tenant, reason="lease_reaped")

    def _start_queued_locked(self) -> None:
        i = 0
        now = self.clock.now()
        while i < len(self._queue):
            tid = self._queue[i]
            tenant = self._tenants[tid]
            # a requeued tenant must not race its own stale thread on
            # the db/checkpoint: wait for that thread to exit first (the
            # capacity stays free for OTHER tenants meanwhile — no
            # head-of-line blocking: we skip, not stall)
            if tenant.thread is not None and tenant.thread.is_alive():
                i += 1
                continue
            # sub-mesh placement: widest free power-of-two divisor of
            # the requested shard count (any width is bit-identical by
            # the kernel contract), width 1 for unsharded tenants.
            # Widths above one host's segment are tried only for
            # explicitly multi-host tenants — everyone else confines to
            # a host and never pays (or risks) DCN.
            lo = width = None
            multi_host = bool(getattr(tenant.spec, "multi_host", False))
            for w in placement.feasible_widths(tenant.spec.sharded):
                if w > self.allocator.devices_per_host and not multi_host:
                    continue
                got = self.allocator.alloc(w, tid, multi_host=multi_host)
                if got is not None:
                    lo, width = got, w
                    break
            if lo is None:
                if tenant._unplaced_since is None:
                    tenant._unplaced_since = now
                i += 1
                continue
            tenant._unplaced_since = None
            del self._queue[i]
            slot = next(self._lease_seq)
            self._lease_slot_of[tid] = slot
            self.leases.grant(tid, slot, slot + 1)
            tenant.submesh_lo, tenant.submesh_width = lo, width
            tenant.widths.append(width)
            if tenant._device_loss_t0 is not None:
                # the device-loss recovery span: loss event -> re-placed
                tenant.tracer.record_span(
                    "device_loss.replace", tenant._device_loss_t0, now,
                    thread="scheduler",
                )
                tenant._device_loss_t0 = None
            tenant.state = RUNNING
            tenant.attempt += 1
            epoch = tenant.epoch
            if tenant.started_at is None:
                tenant.started_at = self.clock.now()
            tenant.record_event("started", lo=lo, width=width,
                                attempt=tenant.attempt)
            tenant.thread = threading.Thread(
                target=self._run_tenant_attempt,
                args=(tenant, epoch),
                daemon=True, name=f"abc-serve-{tid}-a{tenant.attempt}",
            )
            tenant.thread.start()

    def _maybe_lifecycle_sweep_locked(self) -> None:
        """Periodic retention/GC pass, under the scheduler lock so no
        tenant can transition into RUNNING mid-sweep (the GC safety
        contract: a sweep never touches a History whose writer is
        live). RUNNING tenants are skipped inside the sweep."""
        if not self.lifecycle.due():
            return
        self.lifecycle.sweep(list(self._tenants.values()))
        for tenant in self._tenants.values():
            tenant.quota_remaining = self.lifecycle.quota_remaining(tenant)

    def _maybe_auto_preempt_locked(self) -> None:
        """The preemption POLICY: when a queued tenant has been
        unplaceable past ``preempt_queue_wait_s`` (pool fully leased or
        fragmented), checkpoint-preempt the widest running tenant — its
        freed block coalesces, the waiter places, and the preempted
        tenant resumes from its checkpoint later. One in-flight
        preemption at a time (no mass eviction)."""
        if self.preempt_queue_wait_s is None or self._draining:
            return
        if any(t.preempt_requested for t in self._tenants.values()):
            return  # one at a time; wait for it to drain
        now = self.clock.now()
        starved = [
            t for tid in self._queue
            if (t := self._tenants[tid])._unplaced_since is not None
            and now - t._unplaced_since >= self.preempt_queue_wait_s
        ]
        if not starved:
            return
        victims = [
            t for t in self._tenants.values()
            if t.state == RUNNING and not t.cancel_requested
            and t.submesh_width is not None
        ]
        if not victims:
            return
        # widest first (frees the most coalescable capacity), oldest
        # attempt as the tiebreak (most progress already checkpointed)
        victim = max(victims,
                     key=lambda t: (t.submesh_width, -t.attempt))
        self.preempt(victim.id)

    def _release_placement_locked(self, tenant: Tenant,
                                  lease_already_gone: bool = False
                                  ) -> None:
        slot = self._lease_slot_of.pop(tenant.id, None)
        if slot is None:
            return
        if not lease_already_gone:
            self.leases.note_delivery(slot)
        try:
            self.allocator.free(tenant.id)
        except KeyError:
            pass  # defensively idempotent (double release)
        tenant.submesh_lo = tenant.submesh_width = None

    def _dequeue_locked(self, tid: str) -> None:
        try:
            self._queue.remove(tid)
        except ValueError:
            pass

    def _dump_flight_locked(self, tenant: Tenant, reason: str) -> None:
        """Persist the tenant's flight-recorder ring on a fault path.
        ``dump`` never raises (a broken postmortem must not break the
        scheduler); the counter counts files actually written."""
        path = tenant.flight.dump(reason=reason)
        if path is not None:
            self.metrics.counter(
                FLIGHT_DUMPS_TOTAL,
                "flight-recorder files persisted on fault paths",
            ).inc()

    def _finish_locked(self, tenant: Tenant, state: str,
                       error: str | None = None) -> None:
        tenant.state = state
        tenant.error = error
        tenant.finished_at = self.clock.now()
        tenant.abc = None
        tenant.record_event(state, error=error)
        counters = {
            COMPLETED: (TENANT_COMPLETED_TOTAL,
                        "tenants finished with a posterior"),
            FAILED: (TENANT_FAILURES_TOTAL,
                     "tenants failed terminally"),
            DRAINED: (TENANT_DRAINS_TOTAL,
                      "tenants drained gracefully (flush + final "
                      "checkpoint)"),
        }
        if state in counters:
            name, help_ = counters[state]
            self.metrics.counter(name, help_).inc()
        if state == COMPLETED:
            # time-to-posterior SLO accounting: submit -> completed on
            # the injected clock (queue wait + every attempt + requeues)
            self.metrics.histogram(
                TIME_TO_POSTERIOR_HISTOGRAM,
                "submit to posterior-complete latency of finished "
                "tenants (seconds)",
            ).observe(tenant.finished_at - tenant.submitted_at)
        if state in (FAILED, DRAINED):
            # fault paths persist the black box: the last spans, metric
            # deltas and events land on disk for the --postmortem view
            tenant.flight.note("finish", state=state, error=error)
            self._dump_flight_locked(
                tenant, reason=f"finish:{state.lower()}")
        self.lifecycle.bytes_on_disk(tenant)
        tenant.quota_remaining = self.lifecycle.quota_remaining(tenant)
        self._evict_terminal_locked(tenant.id)
        self._set_occupancy_gauges_locked()
        self._wake.notify_all()

    def _evict_terminal_locked(self, tid: str) -> None:
        """Bound terminal-tenant retention: keep the newest
        ``max_terminal_tenants`` finished records for status queries,
        evict the oldest beyond that (tenant record, event ring,
        observability namespace — AND, round 19, the on-disk History
        files through the lifecycle layer: the pre-round-19 eviction
        dropped the record but leaked every evicted tenant's db and
        ``.columnar/`` files forever) — a long-lived serving process
        must not grow with every tenant it has ever finished."""
        self._terminal_order.append(tid)
        self._evict_overflow_locked()

    def _evict_overflow_locked(self) -> None:
        while len(self._terminal_order) > self.max_terminal_tenants:
            old_tid = self._terminal_order.popleft()
            old = self._tenants.get(old_tid)
            if old is None:
                continue
            if old.state not in TERMINAL_STATES:  # resurrection guard
                continue
            if old.thread is not None and old.thread.is_alive():
                # a stale attempt thread is still unwinding (a reaped or
                # cancelled tenant's stop lands only at its next chunk
                # boundary): disposing NOW races its final flush — a
                # write checking out a fresh sqlite connection after the
                # unlink recreates the db as an orphan file. Defer: put
                # it back at the front and retry on a later pump tick;
                # the ring overshoots its cap by the still-unwinding
                # handful, briefly. The registry entry itself is safe to
                # keep — the thread reports into a bumped epoch.
                self._terminal_order.appendleft(old_tid)
                break
            if not old.disposed:
                try:
                    self.lifecycle.dispose(old)
                except OSError:
                    pass  # a locked/vanished file must not kill the pump
            del self._tenants[old_tid]
            unregister_tenant_source(old_tid)

    def _set_occupancy_gauges_locked(self) -> None:
        self.metrics.gauge(
            TENANTS_LIVE_GAUGE,
            "tenants currently holding a sub-mesh lease",
        ).set(len(self._lease_slot_of))
        self.metrics.gauge(
            TENANTS_QUEUED_GAUGE,
            "tenants admitted and waiting for a sub-mesh",
        ).set(len(self._queue))
        place = self.allocator
        self.metrics.gauge(
            SUBMESH_DEVICES_HEALTHY_GAUGE,
            "healthy devices in the serving pool",
        ).set(place.healthy_count())
        self.metrics.gauge(
            SUBMESH_DEVICES_FREE_GAUGE,
            "devices currently in free blocks",
        ).set(place.free_device_count())
        self.metrics.gauge(
            SUBMESH_WIDEST_FREE_GAUGE,
            "widest contiguous sub-mesh allocatable right now",
        ).set(place.widest_free())

    # ------------------------------------------- the leased run (ISO001)
    def _heartbeat(self, tenant: Tenant, epoch: int) -> None:
        """Refresh the tenant's run lease — orchestrator-thread progress
        only (setup milestones + per-chunk events); a hung thread makes
        no progress and its lease expires. NOTE the timeout contract:
        ``lease_timeout_s`` must exceed the worst silent stretch of a
        healthy run (one chunk's compute plus, on a kernel-cache miss,
        the XLA compile) — a DEAD thread is detected immediately via
        thread liveness, the timeout only bounds HANG detection."""
        with self._lock:
            if epoch != tenant.epoch:
                return  # stale attempt: no heartbeat rights
            self.leases.touch_worker(tenant.id)

    def _on_chunk(self, tenant: Tenant, epoch: int, ev: dict) -> None:
        """Per-chunk heartbeat from the tenant's orchestrator thread:
        refresh the run lease, advance progress, feed the event stream."""
        self._heartbeat(tenant, epoch)
        with self._lock:
            if epoch != tenant.epoch:
                return
            # re-assert an acknowledged stop (idempotent): a cancel,
            # preempt or drain that raced run() entry — which clears any
            # pre-run stop request — would otherwise be lost and the run
            # would land COMPLETED despite the ack
            run = (tenant.abc
                   if (tenant.cancel_requested or tenant.preempt_requested
                       or self._draining)
                   else None)
        if run is not None:
            run.request_graceful_stop()
        done = int(ev.get("t_first", 0)) + int(ev.get("gens", 0))
        tenant.generations_done = max(tenant.generations_done, done)
        tenant.record_event(
            "chunk", t_first=ev.get("t_first"), gens=ev.get("gens"),
            n_acc=ev.get("n_acc"), chunk_s=round(ev.get("chunk_s", 0.0), 6),
        )

    def _report(self, tenant: Tenant, epoch: int, outcome: str,
                **payload) -> None:
        with self._lock:
            self._reports.append((tenant.id, epoch, outcome, payload))
            self._wake.notify_all()

    def _run_tenant_attempt(self, tenant: Tenant, epoch: int) -> None:
        """One leased attempt, on its own orchestrator thread — the only
        place in pyabc_tpu/serving/ that builds an ABCSMC (ISO001)."""
        from ..inference.smc import ABCSMC, GracefulShutdown
        from ..resilience.faults import InjectedKill, fault_scope
        from ..resilience.health import DegenerateRunError

        t_run0 = self.clock.now()
        with fault_scope(tenant.id):
            try:
                built = tenant.spec.abcsmc_kwargs()
                # sub-mesh placement -> kernel execution: a physical
                # mesh over the leased devices when the platform has
                # them (width > 1), else the shards run VIRTUALLY on
                # one device — either way the SAME n-shard reduction,
                # bit-identical across attempts at different widths
                place_kwargs: dict = {}
                if tenant.spec.sharded:
                    with self._lock:
                        lo, width = tenant.submesh_lo, tenant.submesh_width
                    place_kwargs = {
                        "sharded": int(tenant.spec.sharded),
                        "mesh": placement.build_mesh(lo or 0, width or 1),
                    }
                abc = ABCSMC(
                    tracer=tenant.tracer, metrics=tenant.metrics,
                    checkpoint_path=tenant.checkpoint_path,
                    **place_kwargs,
                    **built["kwargs"],
                )
                self._heartbeat(tenant, epoch)  # setup milestone: built
                if tenant.abc_id is not None:
                    # requeued attempt: resume this tenant's run — the
                    # PR-5 checkpoint adoption inside run() restores the
                    # mid-chunk carry bit-exact
                    abc.load(tenant.db_path, tenant.abc_id)
                else:
                    abc.new(tenant.db_path, built["observed"],
                            store_sum_stats=tenant.spec.store_sum_stats)
                    tenant.abc_id = int(abc.history.id)
                # per-tenant History stream on the SHARED writer pool,
                # tagged with this tenant's fault domain
                abc.history.writer_pool = self.writer_pool
                abc.history.writer_scope = tenant.id
                self._heartbeat(tenant, epoch)  # setup milestone: db open
                hit = self.kernel_cache.adopt_or_register(abc)
                if tenant.kernel_cache_hit is None:
                    tenant.kernel_cache_hit = hit
                self.metrics.counter(
                    TENANT_KERNEL_CACHE_HITS_TOTAL if hit
                    else TENANT_KERNEL_CACHE_MISSES_TOTAL,
                    "shape-keyed kernel cache hits (zero compile)"
                    if hit else "shape-keyed kernel cache misses",
                ).inc()
                tenant.record_event("kernel_cache",
                                    hit=hit, attempt=tenant.attempt)
                with self._lock:
                    tenant.abc = abc
                    # a cancel (or drain) acknowledged while tenant.abc
                    # was still None set the flag with no run handle to
                    # stop — honor it here or the run proceeds to
                    # COMPLETED despite the ack. Skipping run() (rather
                    # than request_graceful_stop) because run() clears
                    # any pre-run stop request at entry.
                    stop_now = (epoch == tenant.epoch
                                and (tenant.cancel_requested
                                     or tenant.preempt_requested
                                     or self._draining))
                if stop_now:
                    self._report(
                        tenant, epoch, DRAINED,
                        error="stopped before run start",
                        run_s=self.clock.now() - t_run0,
                    )
                    return
                abc.chunk_event_cb = (
                    lambda ev, _t=tenant, _e=epoch:
                    self._on_chunk(_t, _e, ev)
                )
                run_kwargs: dict = {
                    "max_nr_populations": int(tenant.spec.generations),
                }
                if tenant.spec.minimum_epsilon is not None:
                    run_kwargs["minimum_epsilon"] = float(
                        tenant.spec.minimum_epsilon)
                if tenant.spec.max_walltime_s is not None:
                    run_kwargs["max_walltime"] = float(
                        tenant.spec.max_walltime_s)
                h = abc.run(**run_kwargs)
                self.kernel_cache.register_from(abc)
                result = {
                    "n_populations": int(h.n_populations),
                    "total_simulations": int(h.total_nr_simulations),
                }
                self._report(
                    tenant, epoch, COMPLETED, result=result,
                    run_s=self.clock.now() - t_run0,
                )
            except InjectedKill:
                # HARD death: no report, no slot release — the run
                # lease must expire (or the dead thread be noticed) for
                # the scheduler to reclaim and requeue; this is the
                # chaos tests' primary weapon
                return
            except GracefulShutdown as exc:
                self._report(
                    tenant, epoch, DRAINED, error=str(exc),
                    run_s=self.clock.now() - t_run0,
                )
            except DegenerateRunError as exc:
                self._report(
                    tenant, epoch, FAILED,
                    error=f"degenerate run: {exc}", trail=exc.trail,
                    run_s=self.clock.now() - t_run0,
                )
            except BaseException as exc:  # noqa: BLE001 - tenant fault
                # domain boundary: ANY other orchestrator failure is
                # contained to this tenant and reported typed
                self._report(
                    tenant, epoch, FAILED, error=repr(exc)[:500],
                    run_s=self.clock.now() - t_run0,
                )
