"""Live posterior streaming: Arrow-IPC framing of the epsilon trail and
per-generation posterior summaries (round 19).

The NDJSON event tail (``/api/tenant/<id>/stream``) tells a client THAT
a chunk landed; watching CONVERGENCE still meant polling SQL afterwards.
This module closes that gap: as each generation's row (and, for
columnar tenants, its Parquet record batch — PR 13) becomes visible in
the tenant's History, the API pushes one summary — ``(t, epsilon,
per-model posterior means)`` — to the client, framed either as an
Arrow IPC stream (one record batch per generation; the natural wire
format for the columnar store) or as NDJSON lines when pyarrow is
absent on either end.

The summary numbers are BIT-IDENTICAL to a post-hoc ``History`` read on
both stores: means are the float64 dot product of exactly the
``get_distribution`` outputs (same row order, same normalized weights),
epsilons come from the same ``populations`` rows, and float64 survives
Arrow IPC framing exactly.

Wire schema (flattened — one row per (generation, model, parameter),
so K>1 model-selection tenants with per-model parameter spaces need no
nesting)::

    t: int64, epsilon: float64, m: int32, p_model: float64,
    n: int64, param: utf8, mean: float64

Reads-only module: it opens History dbs, never constructs runs
(ISO001 stays with the scheduler).
"""
from __future__ import annotations

import io
import json

import numpy as np

from ..storage.columnar import has_pyarrow

#: content type of the Arrow-framed stream (the NDJSON fallback keeps
#: ``application/x-ndjson`` — clients dispatch on the response header)
ARROW_CONTENT_TYPE = "application/vnd.apache.arrow.stream"
NDJSON_CONTENT_TYPE = "application/x-ndjson"

_SCHEMA_FIELDS = (
    ("t", "int64"), ("epsilon", "float64"), ("m", "int32"),
    ("p_model", "float64"), ("n", "int64"), ("param", "utf8"),
    ("mean", "float64"),
)


# ------------------------------------------------------------- summaries
def generation_summaries(db_url: str, abc_id: int | None = None,
                         t_min: int = 0) -> list[dict]:
    """Per-generation posterior summaries from a tenant History.

    Returns ``[{"t", "epsilon", "models": {m: {"p", "n",
    "means": {param: mean}}}}, ...]`` for every stored generation with
    ``t >= t_min``, in ascending t. The means are float64 dots of the
    ``get_distribution`` contract (post-hoc parity by construction).
    """
    from ..storage import History

    hist = History(db_url, _id=abc_id, wal=False)
    try:
        if hist.id is None:
            return []
        pops = hist.get_all_populations()
        out = []
        for _, row in pops.iterrows():
            t = int(row["t"])
            if t < max(int(t_min), 0):
                continue
            probs = hist.get_model_probabilities(t)
            models = {}
            for m in probs.index:
                p = float(probs.loc[m, "p"])
                if p <= 0:
                    continue
                df, w = hist.get_distribution(m=int(m), t=t)
                means = {
                    str(col): float(np.dot(w, np.asarray(df[col],
                                                         np.float64)))
                    for col in df.columns
                }
                models[int(m)] = {"p": p, "n": int(len(w)),
                                  "means": means}
            out.append({"t": t, "epsilon": float(row["epsilon"]),
                        "models": models})
        return out
    finally:
        hist.close()


def _flatten(summary: dict) -> list[tuple]:
    rows = []
    for m, info in sorted(summary["models"].items()):
        for param, mean in sorted(info["means"].items()):
            rows.append((summary["t"], summary["epsilon"], int(m),
                         info["p"], info["n"], param, mean))
    return rows


# ---------------------------------------------------------- arrow frames
class ArrowSummaryWriter:
    """Incremental Arrow IPC framing: :meth:`frame` turns one summary
    into the next stream chunk (the first call carries the schema
    message), :meth:`finish` yields the end-of-stream marker."""

    def __init__(self):
        import pyarrow as pa

        self._pa = pa
        self._sink = io.BytesIO()
        self._schema = pa.schema(
            [pa.field(name, getattr(pa, typ)())
             for name, typ in _SCHEMA_FIELDS])
        self._writer = pa.ipc.new_stream(self._sink, self._schema)

    def _take(self) -> bytes:
        data = self._sink.getvalue()
        self._sink.seek(0)
        self._sink.truncate()
        return data

    def frame(self, summary: dict) -> bytes:
        pa = self._pa
        rows = _flatten(summary)
        cols = list(zip(*rows)) if rows else [[] for _ in _SCHEMA_FIELDS]
        batch = pa.record_batch(
            [pa.array(list(col), field.type)
             for col, field in zip(cols, self._schema)],
            schema=self._schema)
        self._writer.write_batch(batch)
        return self._take()

    def finish(self) -> bytes:
        self._writer.close()
        return self._take()


def read_arrow_summaries(raw: bytes) -> list[dict]:
    """Client side: reassemble ``generation_summaries``-shaped dicts
    from a complete Arrow IPC stream (bit-exact round trip)."""
    import pyarrow as pa

    out: dict[int, dict] = {}
    with pa.ipc.open_stream(io.BytesIO(raw)) as reader:
        for batch in reader:
            cols = {name: batch.column(i).to_pylist()
                    for i, (name, _) in enumerate(_SCHEMA_FIELDS)}
            for i in range(batch.num_rows):
                t = int(cols["t"][i])
                s = out.setdefault(
                    t, {"t": t, "epsilon": float(cols["epsilon"][i]),
                        "models": {}})
                m = int(cols["m"][i])
                info = s["models"].setdefault(
                    m, {"p": float(cols["p_model"][i]),
                        "n": int(cols["n"][i]), "means": {}})
                info["means"][str(cols["param"][i])] = float(
                    cols["mean"][i])
    return [out[t] for t in sorted(out)]


# --------------------------------------------------------- ndjson frames
def summary_json_line(summary: dict) -> bytes:
    """The pyarrow-absent fallback framing: one NDJSON line per
    generation (float64 exactness via repr round-trip)."""
    payload = {
        "kind": "generation", "t": summary["t"],
        "epsilon": summary["epsilon"],
        "models": {str(m): info for m, info in summary["models"].items()},
    }
    return (json.dumps(payload) + "\n").encode()


def parse_summary_lines(lines) -> list[dict]:
    out = []
    for line in lines:
        obj = json.loads(line)
        if obj.get("kind") != "generation":
            continue
        out.append({
            "t": int(obj["t"]), "epsilon": float(obj["epsilon"]),
            "models": {
                int(m): {"p": float(info["p"]), "n": int(info["n"]),
                         "means": {k: float(v)
                                   for k, v in info["means"].items()}}
                for m, info in obj["models"].items()
            },
        })
    return out


# ----------------------------------------------------------- http client
def stream_posterior(host: str, port: int, tenant_id: str,
                     timeout_s: float = 120.0) -> tuple[str, list[dict]]:
    """Consume ``/api/tenant/<id>/stream?format=arrow`` to completion.

    Returns ``(format, summaries)`` where format is ``"arrow"`` or
    ``"ndjson"`` — the SERVER picks NDJSON when it lacks pyarrow (and a
    pyarrow-less client should pass ``format=summaries`` itself to skip
    the Arrow negotiation entirely).
    """
    import http.client

    want_arrow = has_pyarrow()
    fmt = "arrow" if want_arrow else "summaries"
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    try:
        conn.request(
            "GET", f"/api/tenant/{tenant_id}/stream?format={fmt}")
        resp = conn.getresponse()
        if resp.status != 200:
            raise RuntimeError(
                f"stream failed: HTTP {resp.status} "
                f"{resp.read(300)!r}")
        ctype = resp.getheader("Content-Type", "")
        raw = resp.read()  # http.client de-chunks transparently
        if ctype.startswith(ARROW_CONTENT_TYPE):
            return "arrow", read_arrow_summaries(raw)
        return "ndjson", parse_summary_lines(
            line for line in raw.decode().splitlines() if line.strip())
    finally:
        conn.close()
