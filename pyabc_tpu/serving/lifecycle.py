"""Tenant lifecycle: retention policies, History GC, quotas, archival.

The long-lived serving process owns what pyABC's Redis-brokered sampler
fleet got for free from operator babysitting: BOUNDED DISK and
per-tenant accounting. Every admitted tenant writes a private History
db (plus, for columnar tenants, one Parquet file per generation); a
1000-tenant churn day must not end with 1000 dbs on disk and no policy
saying which bytes matter. This module is that policy, in three parts:

- :class:`RetentionPolicy` — declarative per-process retention:
  ``keep_last_k`` generations per tenant (the resume seam only ever
  needs the LATEST generation + the checkpoint, so any k >= 1 keeps
  requeue-resume bit-identical), ``ttl_s`` for terminal tenants'
  Histories, ``archive_on_complete`` (pack the db + columnar sidecar
  into one tar.gz instead of deleting), and a fleet-wide
  ``total_bytes_budget`` under which the oldest terminal tenants are
  disposed first.
- :class:`TenantQuota` — per-tenant admission limits in the same units
  admission already prices backpressure: chip-seconds (checked against
  the spec's cold-start estimate, tracked against actual spend),
  bytes-on-disk (enforced by the sweep: over-quota tenants have their
  oldest generations GC'd down to the floor), and generations.
- :class:`LifecycleManager` — the scheduler-owned sweeper: the pump
  calls :meth:`sweep` every ``sweep_interval_s`` ON THE INJECTED CLOCK
  (CLOCK001); terminal-tenant eviction routes through :meth:`dispose`
  so evicted tenants' files actually leave the disk (the pre-round-19
  eviction dropped records but leaked every db forever).

GC SAFETY CONTRACT: a sweep never touches a RUNNING tenant's History
(its writer owns the file) and never deletes the newest generation or
the PRE_TIME observed row of any tenant — ``ABCSMC.load`` +
checkpoint adoption need exactly those, so a requeued tenant resumes
bit-identical across any number of sweeps.
"""
from __future__ import annotations

import os
import shutil
from dataclasses import dataclass

from ..observability import SYSTEM_CLOCK
from ..observability.metrics import (
    TENANT_ARCHIVES_TOTAL,
    TENANT_BYTES_ON_DISK_GAUGE,
    TENANT_GENERATIONS_GCED_TOTAL,
    TENANT_QUOTA_REJECTIONS_TOTAL,
)
from ..storage.archive import archive_paths, archive_tenant_db
from .admission import AdmissionRejectedError, spec_chip_seconds_estimate
from .tenant import RUNNING, TERMINAL_STATES


def disk_usage(db_url: str) -> dict:
    """Bytes on disk attributable to one tenant History url:
    ``{"db": ..., "columnar": ..., "archive": ..., "total": ...}``
    (db includes WAL/SHM droppings)."""
    sql_path, col_dir, archive = archive_paths(db_url)
    db_b = 0
    for p in (sql_path, str(sql_path) + "-wal", str(sql_path) + "-shm"):
        if os.path.exists(p):
            db_b += os.path.getsize(p)
    col_b = 0
    if col_dir.is_dir():
        for root, _dirs, files in os.walk(col_dir):
            for f in files:
                col_b += os.path.getsize(os.path.join(root, f))
    ar_b = archive.stat().st_size if archive.is_file() else 0
    return {"db": db_b, "columnar": col_b, "archive": ar_b,
            "total": db_b + col_b + ar_b}


@dataclass
class RetentionPolicy:
    """What to keep, per tenant and fleet-wide. All fields optional —
    the default policy retains everything (pre-round-19 behavior) except
    that EVICTED tenants' files are now always disposed."""

    #: keep only the newest k generations of each non-running tenant's
    #: History (k >= 1; the latest generation + checkpoint are all a
    #: requeue-resume needs). None = never prune generations.
    keep_last_k: int | None = None
    #: dispose a terminal tenant's History this many seconds after it
    #: finished (injected-clock seconds). None = no TTL.
    ttl_s: float | None = None
    #: on dispose, pack the db + columnar sidecar into one tar.gz
    #: (restorable via :func:`pyabc_tpu.storage.restore_tenant_db`)
    #: instead of deleting outright
    archive_on_complete: bool = False
    #: fleet-wide disk budget over every known tenant's files; when
    #: exceeded the sweep disposes the OLDEST-FINISHED terminal tenants
    #: until back under (live tenants are never disposed)
    total_bytes_budget: int | None = None

    def __post_init__(self):
        if self.keep_last_k is not None and int(self.keep_last_k) < 1:
            raise ValueError(
                "keep_last_k must be >= 1: the newest generation is the "
                "resume seam and is never GC'd")


@dataclass
class TenantQuota:
    """Per-tenant resource limits, enforced at admission (chip-seconds
    vs the spec's cold-start estimate, generations vs the requested
    schedule) and by the sweep (bytes-on-disk GC). None = unlimited."""

    max_chip_seconds: float | None = None
    max_bytes_on_disk: int | None = None
    max_generations: int | None = None

    def check_spec(self, spec) -> None:
        """Raise :class:`AdmissionRejectedError` (non-retryable: the
        same spec will fail the same way) when the spec cannot fit."""
        if (self.max_generations is not None
                and int(spec.generations) > int(self.max_generations)):
            raise AdmissionRejectedError(
                f"quota: generations {int(spec.generations)} exceeds "
                f"max_generations {int(self.max_generations)}",
                retry_after_s=None)
        if self.max_chip_seconds is not None:
            est = spec_chip_seconds_estimate(spec)
            if est > float(self.max_chip_seconds):
                raise AdmissionRejectedError(
                    f"quota: estimated {est:.1f} chip-seconds exceeds "
                    f"max_chip_seconds {float(self.max_chip_seconds):.1f}",
                    retry_after_s=None)

    def remaining(self, *, chip_s: float, bytes_on_disk: int,
                  generations_done: int) -> dict:
        """Quota-remaining view for status payloads (None = unlimited)."""
        return {
            "chip_seconds": (
                None if self.max_chip_seconds is None
                else round(max(0.0, float(self.max_chip_seconds)
                               - float(chip_s)), 3)),
            "bytes_on_disk": (
                None if self.max_bytes_on_disk is None
                else max(0, int(self.max_bytes_on_disk)
                         - int(bytes_on_disk))),
            "generations": (
                None if self.max_generations is None
                else max(0, int(self.max_generations)
                         - int(generations_done))),
        }


class LifecycleManager:
    """Retention/GC/quota sweeper owned by the :class:`RunScheduler`.

    The scheduler calls in from three seams: :meth:`admission_check`
    inside ``submit`` (under the scheduler lock), :meth:`sweep` from the
    pump every ``sweep_interval_s``, and :meth:`dispose` from
    terminal-tenant eviction. All timestamps ride the INJECTED clock."""

    def __init__(self, *, policy: RetentionPolicy | None = None,
                 quota: TenantQuota | None = None, clock=None,
                 metrics=None, sweep_interval_s: float = 5.0):
        from ..observability import NULL_METRICS

        self.policy = policy if policy is not None else RetentionPolicy()
        self.quota = quota if quota is not None else TenantQuota()
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self.metrics = metrics if metrics is not None else NULL_METRICS
        self.sweep_interval_s = float(sweep_interval_s)
        self._last_sweep: float | None = None
        #: lifetime accounting the bench lane and tests read directly
        self.generations_gced_total = 0
        self.tenants_disposed_total = 0
        self.archives_total = 0

    def stats(self) -> dict:
        from dataclasses import asdict

        return {
            "policy": asdict(self.policy),
            "quota": asdict(self.quota),
            "sweep_interval_s": self.sweep_interval_s,
            "generations_gced_total": int(self.generations_gced_total),
            "tenants_disposed_total": int(self.tenants_disposed_total),
            "archives_total": int(self.archives_total),
        }

    # -------------------------------------------------------- admission
    def admission_check(self, spec) -> None:
        """Quota gate next to the chip-second backpressure pricing."""
        try:
            self.quota.check_spec(spec)
        except AdmissionRejectedError:
            self.metrics.counter(
                TENANT_QUOTA_REJECTIONS_TOTAL,
                "submissions refused because the tenant quota "
                "(chip-seconds / generations) was exhausted",
            ).inc()
            raise

    # ------------------------------------------------------- accounting
    def bytes_on_disk(self, tenant) -> int:
        """Total bytes of this tenant's History artifacts, exported to
        the tenant's PRIVATE registry (so /metrics labels it)."""
        total = disk_usage(tenant.db_path)["total"]
        ck = tenant.checkpoint_path
        if ck and os.path.exists(ck):
            total += os.path.getsize(ck)
        tenant.bytes_on_disk = int(total)
        tenant.metrics.gauge(
            TENANT_BYTES_ON_DISK_GAUGE,
            "bytes on disk for this tenant's History (db + WAL + "
            "columnar generation files + archive + checkpoint)",
        ).set(float(total))
        return int(total)

    def quota_remaining(self, tenant) -> dict:
        return self.quota.remaining(
            chip_s=float(getattr(tenant, "chip_s", 0.0)),
            bytes_on_disk=int(getattr(tenant, "bytes_on_disk", 0)),
            generations_done=int(tenant.generations_done),
        )

    # ------------------------------------------------------------ sweep
    def due(self) -> bool:
        now = self.clock.now()
        if (self._last_sweep is not None
                and now - self._last_sweep < self.sweep_interval_s):
            return False
        self._last_sweep = now
        return True

    def sweep(self, tenants: list) -> dict:
        """One retention pass over a snapshot of tenant records.

        Returns ``{"pruned": n_generations, "disposed": [ids...]}``.
        RUNNING tenants are skipped entirely (their writer owns the
        History); everything else may be generation-pruned
        (keep-last-k / bytes quota, newest generation always kept) and
        terminal tenants may be disposed (TTL / fleet byte budget)."""
        now = self.clock.now()
        pruned = 0
        disposed: list[str] = []
        for tenant in tenants:
            if tenant.state == RUNNING or tenant.disposed:
                continue
            pruned += self._gc_tenant(tenant)
        # TTL disposal, oldest first
        for tenant in tenants:
            if (self.policy.ttl_s is not None
                    and tenant.state in TERMINAL_STATES
                    and not tenant.disposed
                    and tenant.finished_at is not None
                    and now - tenant.finished_at >= self.policy.ttl_s):
                self.dispose(tenant)
                disposed.append(tenant.id)
        # fleet byte budget: dispose oldest-finished terminal tenants
        if self.policy.total_bytes_budget is not None:
            total = sum(self.bytes_on_disk(t) for t in tenants
                        if not t.disposed)
            if total > self.policy.total_bytes_budget:
                victims = sorted(
                    (t for t in tenants
                     if t.state in TERMINAL_STATES and not t.disposed
                     and t.finished_at is not None),
                    key=lambda t: t.finished_at)
                for victim in victims:
                    if total <= self.policy.total_bytes_budget:
                        break
                    total -= self.dispose(victim)
                    disposed.append(victim.id)
        return {"pruned": pruned, "disposed": disposed}

    def _gc_tenant(self, tenant) -> int:
        """Prune one non-running tenant's oldest generations down to
        the retention floor (keep-last-k, then further only if the
        byte quota demands it — but never below the newest
        generation). Returns generations removed."""
        keep = self.policy.keep_last_k
        over_quota = (
            self.quota.max_bytes_on_disk is not None
            and self.bytes_on_disk(tenant) > self.quota.max_bytes_on_disk
        )
        if keep is None and not over_quota:
            return 0
        sql_path, _, _ = archive_paths(tenant.db_path)
        if not sql_path.is_file():
            return 0  # never started (or already disposed/archived)
        try:
            from ..storage import History

            hist = History(tenant.db_path, _id=tenant.abc_id,
                           wal=False)
            try:
                max_t = hist.max_t
                if max_t < 0:
                    return 0
                removed = 0
                if keep is not None:
                    cut = max_t - int(keep) + 1
                    if cut > 0:
                        removed += hist.prune_before(cut)
                while (over_quota and hist.n_populations > 1):
                    # byte-quota pressure: shed the single oldest
                    # generation at a time until under (or only the
                    # newest — the resume seam — remains)
                    oldest = int(hist.get_all_populations()
                                 .query("t >= 0")["t"].min())
                    removed += hist.prune_before(oldest + 1)
                    hist.vacuum()
                    over_quota = (self.bytes_on_disk(tenant)
                                  > self.quota.max_bytes_on_disk)
                if removed:
                    hist.vacuum()
            finally:
                hist.close()
        except Exception:
            # a requeued tenant's stale attempt may still hold the db
            # (transient sqlite lock): skip this sweep, the next one
            # will catch up
            return 0
        if removed:
            self.generations_gced_total += removed
            self.metrics.counter(
                TENANT_GENERATIONS_GCED_TOTAL,
                "generations deleted by lifecycle retention sweeps "
                "(SQL rows + columnar Parquet files)",
            ).inc(removed)
            tenant.record_event("generations_gced", n=removed)
            self.bytes_on_disk(tenant)
        return removed

    # ---------------------------------------------------------- dispose
    def dispose(self, tenant) -> int:
        """Remove (or archive) one tenant's on-disk artifacts. The
        terminal-eviction seam: called for every evicted tenant, for
        TTL-expired tenants and under fleet byte-budget pressure.
        Returns bytes freed (net of any archive written)."""
        before = self.bytes_on_disk(tenant)
        sql_path, col_dir, archive = archive_paths(tenant.db_path)
        if (self.policy.archive_on_complete
                and tenant.state in TERMINAL_STATES
                and sql_path.is_file()):
            archive_tenant_db(tenant.db_path, remove_original=True)
            self.archives_total += 1
            self.metrics.counter(
                TENANT_ARCHIVES_TOTAL,
                "terminal tenants whose History was packed into a "
                "tar.gz archive",
            ).inc()
            tenant.record_event("archived", path=str(archive))
        else:
            for p in (sql_path, str(sql_path) + "-wal",
                      str(sql_path) + "-shm"):
                if os.path.exists(p):
                    os.unlink(p)
            if col_dir.is_dir():
                shutil.rmtree(col_dir)
        ck = tenant.checkpoint_path
        if ck and os.path.exists(ck):
            os.unlink(ck)
        # flight-recorder dumps belong to the tenant too: a disposed
        # tenant leaving its .flight behind is the same orphan-file
        # leak the eviction guard exists to catch
        fl = getattr(tenant, "flight_path", None)
        if fl and os.path.exists(fl):
            os.unlink(fl)
        tenant.disposed = True
        self.tenants_disposed_total += 1
        after = self.bytes_on_disk(tenant)
        tenant.record_event("disposed", bytes_freed=before - after)
        return before - after
