"""Submit/status/stream HTTP API over a :class:`RunScheduler`.

Grown from ``visserver/server.py``'s stdlib ``ThreadingHTTPServer``
pattern (no Flask in this environment) into the serving front door:

- ``POST /api/submit``            JSON :class:`TenantSpec` -> ``{id}``;
                                  a full queue answers ``429`` with a
                                  measured ``Retry-After`` header
                                  (typed backpressure, never unbounded
                                  queueing)
- ``GET  /api/tenants``           tenants' status + scheduler state;
                                  ``?state=running&offset=0&limit=50``
                                  filters/pages the tenant list (round
                                  19 — listing stays O(page) at fleet
                                  scale)
- ``GET  /api/tenant/<id>``       one tenant's status (state, progress,
                                  lease/requeue history, health trail,
                                  bytes_on_disk + quota-remaining)
- ``GET  /api/tenant/<id>/stream`` chunked NDJSON event tail
                                  (lifecycle + per-chunk progress;
                                  ``?since=<seq>`` resumes, the stream
                                  ends when the tenant is terminal);
                                  ``?format=arrow`` instead streams the
                                  epsilon trail + per-generation
                                  posterior summaries as an Arrow IPC
                                  stream (one record batch per
                                  generation, pushed as each lands;
                                  falls back to NDJSON summary lines
                                  when the server lacks pyarrow) and
                                  ``?format=summaries`` requests the
                                  NDJSON summary framing directly
- ``GET  /api/tenant/<id>/flight`` the tenant's live flight-recorder
                                  snapshot (round 22): bounded rings of
                                  recent spans, metric deltas, events
                                  and federated host spans — the same
                                  payload a fault-path dump persists
- ``POST /api/tenant/<id>/cancel`` cancel (graceful for running runs)
- ``POST /api/tenant/<id>/preempt`` checkpoint-preempt a running
                                  tenant: it stops at its next chunk
                                  boundary, requeues with its
                                  checkpoint, and resumes on whatever
                                  sub-mesh is next free (409 when not
                                  running)
- ``GET  /api/observability``     the process snapshot — per-tenant
                                  namespaces aggregated side by side
- ``GET  /metrics``               Prometheus text: the global registry
                                  plus every live tenant's private
                                  registry rendered with a
                                  ``{tenant="<id>"}`` label

Security posture: binds loopback by default and trusts its callers —
the same stance as the visserver dashboard; a production deployment
fronts it with real auth.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .admission import AdmissionRejectedError
from .scheduler import RunScheduler
from .tenant import TERMINAL_STATES, TenantSpec


def _query_params(query: str) -> dict:
    out: dict[str, str] = {}
    for kv in (query or "").split("&"):
        if not kv:
            continue
        k, _, v = kv.partition("=")
        out[k] = v
    return out


def _make_handler(sched: RunScheduler):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet by default
            pass

        # ----------------------------------------------------- plumbing
        def _send(self, code: int, payload: bytes,
                  ctype: str = "application/json",
                  headers: dict | None = None) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            for k, v in (headers or {}).items():
                self.send_header(k, str(v))
            self.end_headers()
            self.wfile.write(payload)

        def _json(self, code: int, obj,
                  headers: dict | None = None) -> None:
            self._send(code, json.dumps(obj, default=str).encode(),
                       headers=headers)

        def _body(self) -> dict:
            n = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(n) if n else b"{}"
            return json.loads(raw.decode() or "{}")

        # ------------------------------------------------------- routes
        def do_POST(self):  # noqa: N802 - stdlib API
            try:
                if self.path == "/api/submit":
                    return self._submit()
                if (self.path.startswith("/api/tenant/")
                        and self.path.endswith("/cancel")):
                    tid = self.path[len("/api/tenant/"):-len("/cancel")]
                    ok = sched.cancel(tid)
                    return self._json(200 if ok else 404,
                                      {"cancelled": ok, "id": tid})
                if (self.path.startswith("/api/tenant/")
                        and self.path.endswith("/preempt")):
                    tid = self.path[len("/api/tenant/"):-len("/preempt")]
                    ok = sched.preempt(tid)
                    return self._json(200 if ok else 409,
                                      {"preempted": ok, "id": tid})
                self._json(404, {"error": "not found"})
            except Exception as exc:  # surface as 500, keep serving
                self._json(500, {"error": repr(exc)[:300]})

        def do_GET(self):  # noqa: N802 - stdlib API
            try:
                if (self.path == "/api/tenants"
                        or self.path.startswith("/api/tenants?")):
                    q = _query_params(self.path.partition("?")[2])
                    limit = q.get("limit")
                    return self._json(200, sched.snapshot(
                        state=q.get("state") or None,
                        offset=int(q.get("offset") or 0),
                        limit=None if limit in (None, "") else int(limit),
                    ))
                if self.path == "/api/observability":
                    from ..observability import observability_snapshot

                    return self._json(200, observability_snapshot())
                if self.path == "/metrics":
                    return self._metrics()
                if self.path.startswith("/api/tenant/"):
                    rest = self.path[len("/api/tenant/"):]
                    if rest.endswith("/stream") or "/stream?" in rest:
                        tid, _, q = rest.partition("/stream")
                        return self._stream(tid, q.lstrip("?"))
                    if rest.endswith("/flight"):
                        tid = rest[:-len("/flight")]
                        tenant = sched.get(tid)
                        if tenant is None:
                            return self._json(
                                404, {"error": "unknown tenant",
                                      "id": tid})
                        # on-demand flight snapshot: the same payload a
                        # fault-path dump persists, straight off the
                        # live rings (no file round-trip)
                        return self._json(
                            200,
                            tenant.flight.snapshot(reason="api"))
                    status = sched.status(rest)
                    if status is None:
                        return self._json(404, {"error": "unknown tenant",
                                                "id": rest})
                    return self._json(200, status)
                self._json(404, {"error": "not found"})
            except BrokenPipeError:  # client went away mid-stream
                pass
            except Exception as exc:
                self._json(500, {"error": repr(exc)[:300]})

        # ------------------------------------------------------ handlers
        def _submit(self) -> None:
            body = self._body()
            try:
                spec = TenantSpec.from_dict(body)
            except (TypeError, ValueError) as exc:
                return self._json(400, {"error": f"bad spec: {exc}"})
            try:
                tenant = sched.submit(spec)
            except AdmissionRejectedError as exc:
                code = 429 if exc.retry_after_s is not None else 400
                headers = (
                    {"Retry-After": f"{exc.retry_after_s:.0f}"}
                    if exc.retry_after_s is not None else None
                )
                return self._json(
                    code,
                    {"error": exc.reason,
                     "retry_after_s": exc.retry_after_s},
                    headers=headers,
                )
            self._json(200, {"id": tenant.id,
                             "state": tenant.state,
                             "db": tenant.db_path})

        def _metrics(self) -> None:
            from ..observability import global_metrics
            from ..observability.export import prometheus_text

            parts = [prometheus_text(global_metrics())]
            for st in sched.snapshot()["tenants"]:
                tenant = sched.get(st["id"])
                if tenant is not None:
                    parts.append(prometheus_text(
                        tenant.metrics, labels={"tenant": tenant.id}))
            self._send(200, "".join(parts).encode(),
                       ctype="text/plain; version=0.0.4")

        def _start_chunked(self, ctype: str) -> None:
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

        def _write_chunk(self, data: bytes) -> None:
            if not data:
                return  # a zero-length chunk would terminate the body
            self.wfile.write(f"{len(data):X}\r\n".encode())
            self.wfile.write(data + b"\r\n")
            self.wfile.flush()

        def _end_chunked(self) -> None:
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()

        def _stream(self, tid: str, query: str) -> None:
            tenant = sched.get(tid)
            if tenant is None:
                return self._json(404, {"error": "unknown tenant",
                                        "id": tid})
            params = _query_params(query)
            if params.get("format") in ("arrow", "summaries"):
                return self._stream_posterior(
                    tenant, want_arrow=params["format"] == "arrow")
            since = params.get("since", "")
            seq = int(since) if since.isdigit() else 0
            self._start_chunked("application/x-ndjson")
            while True:
                events = tenant.events_since(seq, timeout_s=1.0)
                for ev in events:
                    seq = max(seq, int(ev["seq"]))
                    self._write_chunk(
                        (json.dumps(ev, default=str) + "\n").encode())
                if not events and tenant.state in TERMINAL_STATES:
                    break
            self._write_chunk(
                (json.dumps({"kind": "end", "state": tenant.state})
                 + "\n").encode())
            self._end_chunked()

        def _stream_posterior(self, tenant, want_arrow: bool) -> None:
            """Push the epsilon trail + per-generation posterior
            summaries as each generation becomes visible in the
            tenant's History: Arrow IPC framing (one record batch per
            generation) when pyarrow is available server-side, NDJSON
            summary lines otherwise — the client dispatches on the
            Content-Type."""
            from ..storage.columnar import has_pyarrow
            from . import streaming

            use_arrow = want_arrow and has_pyarrow()
            writer = streaming.ArrowSummaryWriter() if use_arrow else None
            self._start_chunked(
                streaming.ARROW_CONTENT_TYPE if use_arrow
                else streaming.NDJSON_CONTENT_TYPE)
            seq = 0
            next_t = 0
            while True:
                events = tenant.events_since(seq, timeout_s=1.0)
                for ev in events:
                    seq = max(seq, int(ev["seq"]))
                terminal = tenant.state in TERMINAL_STATES
                if tenant.abc_id is not None and not tenant.disposed:
                    try:
                        summaries = streaming.generation_summaries(
                            tenant.db_path, abc_id=tenant.abc_id,
                            t_min=next_t)
                    except Exception:
                        # transient read-under-write (sqlite lock) or a
                        # GC race: retry on the next wakeup
                        summaries = []
                    for s in summaries:
                        next_t = max(next_t, s["t"] + 1)
                        self._write_chunk(
                            writer.frame(s) if use_arrow
                            else streaming.summary_json_line(s))
                if terminal and not events:
                    break
            if use_arrow:
                self._write_chunk(writer.finish())
            else:
                self._write_chunk(
                    (json.dumps({"kind": "end", "state": tenant.state})
                     + "\n").encode())
            self._end_chunked()

    return Handler


def serve_api(sched: RunScheduler, host: str = "127.0.0.1",
              port: int = 8766, block: bool = True) -> ThreadingHTTPServer:
    """Serve the tenant API over ``sched``; ``block=False`` runs it on a
    daemon thread and returns the server (tests / embedding)."""
    httpd = ThreadingHTTPServer((host, port), _make_handler(sched))
    if block:  # pragma: no cover - manual invocation
        print(f"abc-serve API on http://{host}:{httpd.server_port}")
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            httpd.server_close()
        return httpd
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    return httpd
