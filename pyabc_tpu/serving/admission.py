"""Bounded admission with typed backpressure.

A serving process protecting millions of users cannot queue unboundedly:
past the configured depth a submission is REFUSED with
:class:`AdmissionRejectedError` carrying a ``retry_after_s`` estimate
(the HTTP API maps it to ``429 Too Many Requests`` + ``Retry-After``),
so load sheds at the front door with an honest signal instead of
accumulating latent work that times out after the client stopped
caring. Admission also validates the spec — a malformed tenant is
rejected before it costs a queue slot, let alone a device slot.

The retry-after estimate is measured, not guessed: completed runs feed
an exponentially-weighted per-run wall clock, and the hint is
``queue_ahead x avg_run_s / n_slots`` (floored at 1 s) — the time until
a freed slot plausibly reaches a NEW submission.
"""
from __future__ import annotations

import threading

from ..observability.metrics import (
    TENANT_ADMISSIONS_TOTAL,
    TENANT_REJECTIONS_TOTAL,
)


class AdmissionRejectedError(RuntimeError):
    """Typed backpressure: the queue is full (or the spec is invalid —
    then ``retry_after_s`` is None: retrying the same bad spec later
    will not help)."""

    def __init__(self, reason: str, retry_after_s: float | None = None):
        super().__init__(reason)
        self.reason = reason
        self.retry_after_s = (
            None if retry_after_s is None else float(retry_after_s)
        )


class AdmissionController:
    """Validates specs and enforces the bounded-queue contract.

    Owned by the scheduler (which reports queue/live occupancy at each
    ``admit`` call); thread-safe — API handler threads race submissions
    against the scheduler pump by design.
    """

    def __init__(self, *, max_queued: int = 16, n_slots: int = 1,
                 clock=None, metrics=None, avg_run_s0: float = 5.0):
        from ..observability import NULL_METRICS, SYSTEM_CLOCK

        self.max_queued = int(max_queued)
        self.n_slots = max(int(n_slots), 1)
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self.metrics = metrics if metrics is not None else NULL_METRICS
        self._lock = threading.Lock()
        self._avg_run_s = float(avg_run_s0)  # abc-lint: guarded-by=_lock
        self.admitted_total = 0
        self.rejected_total = 0

    # ------------------------------------------------------------- policy
    def admit(self, spec, *, queued_now: int, live_now: int) -> None:
        """Raise :class:`AdmissionRejectedError` or return (admitted).

        ``queued_now``/``live_now`` are the scheduler's occupancy at the
        instant of the call (it holds its own lock around submit)."""
        try:
            spec.validate()
        except ValueError as exc:
            self._reject()
            raise AdmissionRejectedError(
                f"invalid spec: {exc}", retry_after_s=None
            ) from exc
        if queued_now >= self.max_queued:
            retry = self.retry_after_s(queued_now)
            self._reject()
            raise AdmissionRejectedError(
                f"admission queue full ({queued_now}/{self.max_queued} "
                f"queued, {live_now} live): retry after ~{retry:.1f}s",
                retry_after_s=retry,
            )
        with self._lock:
            self.admitted_total += 1
        self.metrics.counter(
            TENANT_ADMISSIONS_TOTAL,
            "tenant submissions admitted (queued or started)",
        ).inc()

    def retry_after_s(self, queued_now: int) -> float:
        """Measured backpressure hint: how long until a new submission
        plausibly reaches a device slot."""
        with self._lock:
            avg = self._avg_run_s
        return max(1.0, (int(queued_now) + 1) * avg / self.n_slots)

    def note_run_seconds(self, run_s: float) -> None:
        """Feed one completed run's wall clock into the EW average the
        retry-after hint derives from."""
        run_s = max(float(run_s), 0.0)
        with self._lock:
            self._avg_run_s = 0.7 * self._avg_run_s + 0.3 * run_s

    def _reject(self) -> None:
        with self._lock:
            self.rejected_total += 1
        self.metrics.counter(
            TENANT_REJECTIONS_TOTAL,
            "tenant submissions rejected with typed backpressure",
        ).inc()

    def stats(self) -> dict:
        with self._lock:
            return {
                "max_queued": self.max_queued,
                "admitted_total": self.admitted_total,
                "rejected_total": self.rejected_total,
                "avg_run_s": round(self._avg_run_s, 3),
            }
