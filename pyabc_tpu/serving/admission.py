"""Bounded admission with typed backpressure, priced in CHIP-SECONDS.

A serving process protecting millions of users cannot queue unboundedly:
past the configured depth a submission is REFUSED with
:class:`AdmissionRejectedError` carrying a ``retry_after_s`` estimate
(the HTTP API maps it to ``429 Too Many Requests`` + ``Retry-After``),
so load sheds at the front door with an honest signal instead of
accumulating latent work that times out after the client stopped
caring. Admission also validates the spec — a malformed tenant is
rejected before it costs a queue slot, let alone a device slot.

Round 15 (mesh-aware serving): queue POSITION stopped being the unit of
cost the moment slots became sub-meshes — a width-4 tenant ahead of you
consumes four chips for its whole run, a packed width-1 tenant a
fraction of one. The backpressure hint is therefore priced in
chip-seconds over the POOL: completed runs feed an exponentially
weighted per-run CHIP-second average (wall seconds × lease width), and
the hint is ``queue_depth × avg_chip_seconds / healthy_chips`` (floored
at 1 s) — the time until the pool plausibly works off the backlog ahead
of a new submission. Device loss shrinks ``healthy_chips`` via
:meth:`AdmissionController.set_capacity`, so the same queue honestly
costs more after the mesh halves.

Cold start: with ZERO completed runs the EW average is unseeded and a
measured hint would degenerate to nothing. The first hints are instead
seeded from the REJECTED spec itself — its generation schedule gives
the chunk count (``ceil(generations / fused_generations)``), priced at
:data:`DEFAULT_CHUNK_S` per chunk and scaled by population size — so
the very first 429 already carries an honest, spec-shaped Retry-After.
"""
from __future__ import annotations

import math
import threading

from ..observability.metrics import (
    TENANT_ADMISSIONS_TOTAL,
    TENANT_REJECTIONS_TOTAL,
)

#: cold-start price of one fused chunk at the reference population —
#: deliberately conservative (a CPU-ish steady-state chunk; real XLA
#: compiles cost more once, measured averages take over immediately
#: after the first completion)
DEFAULT_CHUNK_S = 2.0
#: population the per-chunk estimate is calibrated at; bigger
#: populations scale the cold-start estimate linearly
REFERENCE_POP = 1000


class AdmissionRejectedError(RuntimeError):
    """Typed backpressure: the queue is full (or the spec is invalid —
    then ``retry_after_s`` is None: retrying the same bad spec later
    will not help)."""

    def __init__(self, reason: str, retry_after_s: float | None = None):
        super().__init__(reason)
        self.reason = reason
        self.retry_after_s = (
            None if retry_after_s is None else float(retry_after_s)
        )


def spec_chip_seconds_estimate(spec) -> float:
    """A spec's cold-start chip-second price, from its population
    schedule: chunks (``ceil(generations / fused_generations)``) ×
    :data:`DEFAULT_CHUNK_S`, scaled linearly above :data:`REFERENCE_POP`.
    Chip-seconds, not wall seconds: a sharded run spreads the same work
    over more chips, it does not shrink it."""
    gens = max(int(getattr(spec, "generations", 1)), 1)
    fused = max(int(getattr(spec, "fused_generations", 1)), 1)
    pop = max(int(getattr(spec, "population_size", REFERENCE_POP)), 2)
    chunks = math.ceil(gens / fused)
    return chunks * DEFAULT_CHUNK_S * max(1.0, pop / REFERENCE_POP)


class AdmissionController:
    """Validates specs and enforces the bounded-queue contract.

    Owned by the scheduler (which reports queue/live occupancy at each
    ``admit`` call and completed chip-seconds per run); thread-safe —
    API handler threads race submissions against the scheduler pump by
    design.
    """

    def __init__(self, *, max_queued: int = 16, n_chips: int = 1,
                 clock=None, metrics=None, n_hosts: int = 1):
        from ..observability import NULL_METRICS, SYSTEM_CLOCK

        self.max_queued = int(max_queued)
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self.metrics = metrics if metrics is not None else NULL_METRICS
        self._lock = threading.Lock()
        #: ``n_chips`` is the FLEET total across all hosts (round 18):
        #: chip-seconds price identically wherever the chip lives, so
        #: host loss is just a big ``set_capacity`` step. ``n_hosts``
        #: rides along for observability — the dashboard reads capacity
        #: as hosts × chips-per-host.
        self.n_hosts = max(int(n_hosts), 1)
        self._n_chips = max(int(n_chips), 1)  # abc-lint: guarded-by=_lock
        #: EW-averaged chip-seconds per completed run; None until the
        #: first completion (cold start: spec-seeded hints)
        self._avg_chip_s: float | None = None  # abc-lint: guarded-by=_lock
        self.admitted_total = 0
        self.rejected_total = 0

    # ------------------------------------------------------------- policy
    def admit(self, spec, *, queued_now: int, live_now: int) -> None:
        """Raise :class:`AdmissionRejectedError` or return (admitted).

        ``queued_now``/``live_now`` are the scheduler's occupancy at the
        instant of the call (it holds its own lock around submit)."""
        try:
            spec.validate()
        except ValueError as exc:
            self._reject()
            raise AdmissionRejectedError(
                f"invalid spec: {exc}", retry_after_s=None
            ) from exc
        if queued_now >= self.max_queued:
            retry = self.retry_after_s(queued_now, spec=spec)
            self._reject()
            raise AdmissionRejectedError(
                f"admission queue full ({queued_now}/{self.max_queued} "
                f"queued, {live_now} live): retry after ~{retry:.1f}s",
                retry_after_s=retry,
            )
        with self._lock:
            self.admitted_total += 1
        self.metrics.counter(
            TENANT_ADMISSIONS_TOTAL,
            "tenant submissions admitted (queued or started)",
        ).inc()

    def retry_after_s(self, queued_now: int, spec=None) -> float:
        """Measured backpressure hint in wall seconds: the chip-second
        backlog ahead of a new submission, worked off by the healthy
        pool — ``queue_depth × avg_chip_s / healthy_chips``. Before any
        run has completed the average is seeded from the spec's own
        schedule (:func:`spec_chip_seconds_estimate`)."""
        with self._lock:
            avg = self._avg_chip_s
            chips = self._n_chips
        if avg is None:
            avg = (spec_chip_seconds_estimate(spec)
                   if spec is not None else DEFAULT_CHUNK_S)
        return max(1.0, (int(queued_now) + 1) * avg / chips)

    def note_run_seconds(self, run_s: float, chips: int = 1) -> None:
        """Feed one completed run's cost into the EW average: wall
        seconds × the width of the sub-mesh it held = chip-seconds."""
        chip_s = max(float(run_s), 0.0) * max(int(chips), 1)
        with self._lock:
            if self._avg_chip_s is None:
                self._avg_chip_s = chip_s
            else:
                self._avg_chip_s = 0.7 * self._avg_chip_s + 0.3 * chip_s

    def set_capacity(self, n_chips: int) -> None:
        """The pool changed size (device loss / restore): the SAME
        backlog now honestly costs ``old/new`` times as much wall
        time."""
        with self._lock:
            self._n_chips = max(int(n_chips), 1)

    def _reject(self) -> None:
        with self._lock:
            self.rejected_total += 1
        self.metrics.counter(
            TENANT_REJECTIONS_TOTAL,
            "tenant submissions rejected with typed backpressure",
        ).inc()

    def stats(self) -> dict:
        with self._lock:
            return {
                "max_queued": self.max_queued,
                "admitted_total": self.admitted_total,
                "rejected_total": self.rejected_total,
                "n_chips": self._n_chips,
                "n_hosts": self.n_hosts,
                "avg_chip_s": (
                    None if self._avg_chip_s is None
                    else round(self._avg_chip_s, 3)
                ),
                "cold_start": self._avg_chip_s is None,
            }
