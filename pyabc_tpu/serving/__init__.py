"""pyabc_tpu.serving — multi-tenant ABC-SMC serving with hard fault
isolation (round 14).

The ROADMAP's heavy-traffic north star made concrete: one process,
shared device slots, MANY concurrent ABC-SMC runs as leased TENANTS.
The subsystem composes the production spine the previous rounds built —
run-level leases reuse :class:`~pyabc_tpu.resilience.lease.LeaseTable`
(PR 5), containment rides the per-run RunSupervisor/checkpoint/
GracefulShutdown machinery (PR 5/6), per-tenant observability
namespaces extend ``observability_snapshot()`` (PR 1), and admission
hits a shape-keyed compiled-kernel cache (PR 2's adoption, generalized)
— into four pieces:

- :mod:`.tenant` — :class:`TenantSpec` (declarative, JSON-postable) and
  :class:`Tenant` (supervised runtime record + private tracer/metrics
  namespace);
- :mod:`.admission` — :class:`AdmissionController`: bounded queueing
  with typed backpressure (:class:`AdmissionRejectedError` carrying a
  chip-second-priced ``retry_after_s``);
- :mod:`.placement` — :class:`SubMeshAllocator` (round 15): buddy
  allocation of contiguous 1/2/4/8-device sub-meshes with coalescing,
  width-1 packing, device-loss quarantine and degraded cordons — the
  ONE sanctioned Mesh/topology site in the package (PLACE001);
- :mod:`.scheduler` — :class:`RunScheduler`: sub-mesh leasing,
  orchestrator threads under per-tenant fault scopes, lease-expiry
  requeue from checkpoints, checkpoint-preemption, device-loss
  survival, graceful SIGTERM drain;
- :mod:`.api` — the ``abc-serve`` HTTP surface (submit/status/stream/
  preempt, ``/metrics`` with per-tenant labels);
- :mod:`.lifecycle` — :class:`RetentionPolicy` / :class:`TenantQuota` /
  :class:`LifecycleManager` (round 19): keep-last-k / TTL / archive
  retention, History GC (SQL rows + columnar Parquet files), fleet
  disk budgets and per-tenant quota accounting — bounded disk for a
  long-lived process under sustained churn;
- :mod:`.streaming` — Arrow-IPC (or NDJSON-fallback) framing of the
  epsilon trail + per-generation posterior summaries, pushed live over
  ``/api/tenant/<id>/stream?format=arrow``.

The headline contract, chaos-tested on CPU in ``tests/test_serving.py``
and guarded by the bench ``serve`` lane: a fault injected into tenant A
(any PR-5/PR-6 kind, at any site) never stalls, corrupts or starves
tenant B.
"""
from .admission import AdmissionController, AdmissionRejectedError
from .api import serve_api
from .lifecycle import LifecycleManager, RetentionPolicy, TenantQuota
from .placement import SubMeshAllocator, feasible_widths
from .scheduler import RunScheduler
from .streaming import generation_summaries, stream_posterior
from .tenant import (
    CANCELLED,
    COMPLETED,
    DRAINED,
    FAILED,
    MODEL_BUILDERS,
    QUEUED,
    REQUEUED,
    RUNNING,
    TERMINAL_STATES,
    Tenant,
    TenantSpec,
)

__all__ = [
    "AdmissionController", "AdmissionRejectedError",
    "RunScheduler", "serve_api",
    "LifecycleManager", "RetentionPolicy", "TenantQuota",
    "generation_summaries", "stream_posterior",
    "SubMeshAllocator", "feasible_widths",
    "Tenant", "TenantSpec", "MODEL_BUILDERS",
    "QUEUED", "RUNNING", "REQUEUED", "COMPLETED", "FAILED",
    "CANCELLED", "DRAINED", "TERMINAL_STATES",
]
