"""Sub-mesh placement: the buddy allocator behind topology-aware slots.

Round 15 (mesh-aware serving): a serving "slot" stops being one opaque
device and becomes a CONTIGUOUS SUB-MESH of 1/2/4/8 devices. This
module owns all of the topology:

- :class:`SubMeshAllocator` — a buddy-style allocator over the device
  line. Width-``2^k`` blocks live at aligned offsets; an allocation
  splits the smallest sufficient free block down to the requested
  width, a free merges buddies back up (coalescing), so fragmentation
  is bounded by the buddy invariant instead of accumulating. Width-1
  blocks can be SHARED by up to ``packing`` tenants (many small tenants
  per chip); wider blocks are exclusive (a sharded tenant owns its
  sub-mesh). Devices can be marked LOST (reaped from capacity —
  quarantined on free, never re-issued) or DEGRADED (cordoned: existing
  leases drain naturally, no new placements).

  Round 18 (multi-host fleets): the device line becomes PER-HOST
  SEGMENTS — ``n_hosts`` equal runs of ``devices_per_host`` devices.
  Buddy alignment makes host confinement free: with a power-of-two
  segment, any aligned block of width <= devices_per_host sits inside
  exactly one host, so only widths ABOVE the segment can straddle
  hosts — and those need an explicit ``multi_host=True`` lease (a
  tenant that opted into DCN collectives). Host loss
  (:meth:`mark_host_lost`) is device loss for the whole segment at
  once: every lease touching it is reported for reaping, the segment
  quarantines, capacity shrinks by ``devices_per_host``.
- :func:`feasible_widths` — the placement policy for a ``sharded=n``
  request: the PR-9/15 kernel contract makes the reduction a pure
  function of ``n_shards``, so ANY power-of-two divisor width works
  bit-identically; the scheduler tries them widest-first.
- :func:`build_mesh` / :func:`platform_device_count` — the ONE place in
  ``pyabc_tpu/serving/`` allowed to construct a ``jax.sharding.Mesh``
  or enumerate devices (abc-lint PLACE001): placement decisions
  anywhere else would bypass the allocator's accounting exactly the
  way an unleased run bypasses ISO001.

The allocator is pure bookkeeping with NO lock of its own — the owning
:class:`~pyabc_tpu.serving.scheduler.RunScheduler` mutates it under its
scheduler lock, same contract as the resilience ``LeaseTable``.
"""
from __future__ import annotations

import numpy as np


def _is_pow2(n: int) -> bool:
    return n >= 1 and (n & (n - 1)) == 0


def _aligned_blocks(lo: int, hi: int) -> list[tuple[int, int]]:
    """Greedy aligned power-of-two decomposition of ``[lo, hi)`` —
    the buddy seed for any pool width (power of two or not)."""
    out = []
    while lo < hi:
        size = 1
        while lo % (2 * size) == 0 and lo + 2 * size <= hi:
            size *= 2
        out.append((lo, size))
        lo += size
    return out


class SubMeshAllocator:
    """Buddy allocation of contiguous device ranges with shared width-1
    blocks, loss quarantine and degraded cordons.

    Every device index is, at all times, in exactly ONE of: a free
    block, a shared width-1 block, an exclusive lease, or the lost set
    — :meth:`check_invariants` recomputes that partition from scratch
    and is asserted by the serving tests and the bench ``serve`` lane
    ("zero leaked/overlapping device ranges").
    """

    def __init__(self, n_devices: int, *, packing: int = 1,
                 n_hosts: int = 1):
        self.n_devices = int(n_devices)
        if self.n_devices < 1:
            raise ValueError("need at least one device")
        self.n_hosts = max(int(n_hosts), 1)
        if self.n_devices % self.n_hosts:
            raise ValueError(
                f"{self.n_devices} devices do not split evenly over "
                f"{self.n_hosts} hosts")
        self.devices_per_host = self.n_devices // self.n_hosts
        if self.n_hosts > 1 and not _is_pow2(self.devices_per_host):
            raise ValueError(
                f"devices_per_host={self.devices_per_host} must be a "
                f"power of two for host-confined buddy blocks")
        self.packing = max(int(packing), 1)
        #: width -> sorted list of free block offsets
        self._free: dict[int, list[int]] = {}
        for lo, size in _aligned_blocks(0, self.n_devices):
            self._free.setdefault(size, []).append(lo)
        #: owner -> (lo, width) for exclusive (width >= 2 or unshared) leases
        self._exclusive: dict[str, tuple[int, int]] = {}
        #: width-1 block offset -> set of owners sharing it
        self._shared: dict[int, set[str]] = {}
        self._owner_shared: dict[str, int] = {}
        self._lost: set[int] = set()
        self._degraded: set[int] = set()
        self._lost_hosts: set[int] = set()
        # lifetime counters (observability)
        self.allocs_total = 0
        self.frees_total = 0
        self.coalesces_total = 0
        self.devices_lost_total = 0
        self.hosts_lost_total = 0

    def host_of(self, device: int) -> int:
        """Which host segment device ``device`` lives in."""
        return int(device) // self.devices_per_host

    # ------------------------------------------------------------ alloc
    def alloc(self, width: int, owner: str, *,
              multi_host: bool = False) -> int | None:
        """Lease a contiguous ``width``-device sub-mesh to ``owner``;
        returns the base device index, or None when nothing fits now
        (the tenant stays queued). Width 1 packs into a shared block
        when ``packing > 1``. On a multi-host pool, widths above
        ``devices_per_host`` straddle host segments (whole hosts, DCN
        collectives in the tenant's critical path) and need an explicit
        ``multi_host=True``."""
        width = int(width)
        owner = str(owner)
        if not _is_pow2(width):
            raise ValueError(f"sub-mesh width must be a power of two, "
                             f"got {width}")
        if self.n_hosts > 1 and width > self.devices_per_host \
                and not multi_host:
            raise ValueError(
                f"width {width} spans "
                f"{width // self.devices_per_host} host segments of "
                f"{self.devices_per_host} devices; sub-mesh leases never "
                f"straddle hosts implicitly — pass multi_host=True for "
                f"an explicitly multi-host sharded tenant")
        if owner in self._exclusive or owner in self._owner_shared:
            raise ValueError(f"owner {owner!r} already holds a lease")
        if width == 1 and self.packing > 1:
            # densest shared block first: keeps whole devices free for
            # wide sub-meshes instead of spreading singles around
            best = None
            for lo, owners in self._shared.items():
                if len(owners) >= self.packing or lo in self._degraded:
                    continue
                if best is None or len(owners) > len(self._shared[best]):
                    best = lo
            if best is not None:
                self._shared[best].add(owner)
                self._owner_shared[owner] = best
                self.allocs_total += 1
                return best
            lo = self._carve(1)
            if lo is None:
                return None
            self._shared[lo] = {owner}
            self._owner_shared[owner] = lo
            self.allocs_total += 1
            return lo
        lo = self._carve(width)
        if lo is None:
            return None
        self._exclusive[owner] = (lo, width)
        self.allocs_total += 1
        return lo

    def _carve(self, width: int) -> int | None:
        """Carve an aligned free block of exactly ``width``, splitting
        the smallest sufficient larger block buddy-style. Degraded
        devices are cordoned at sub-block granularity: a half-degraded
        big block still serves requests that fit its clean half."""
        found = None  # (size, block_lo, clean_sub_lo)
        for size in sorted(self._free):
            if size < width:
                continue
            for lo in sorted(self._free[size]):
                sub = next(
                    (s for s in range(lo, lo + size, width)
                     if not self._range_degraded(s, width)), None)
                if sub is not None:
                    found = (size, lo, sub)
                    break
            if found:
                break
        if found is None:
            return None
        size, lo, sub = found
        self._free[size].remove(lo)
        # split down toward the clean sub-block, freeing the siblings
        while size > width:
            size //= 2
            if sub < lo + size:
                self._free.setdefault(size, []).append(lo + size)
            else:
                self._free.setdefault(size, []).append(lo)
                lo += size
        return lo

    def _range_degraded(self, lo: int, size: int) -> bool:
        return any(d in self._degraded for d in range(lo, lo + size))

    # ------------------------------------------------------------- free
    def free(self, owner: str) -> None:
        """Return ``owner``'s sub-mesh; buddies coalesce, lost devices
        quarantine (they never re-enter a free list)."""
        owner = str(owner)
        lo1 = self._owner_shared.pop(owner, None)
        if lo1 is not None:
            owners = self._shared[lo1]
            owners.discard(owner)
            self.frees_total += 1
            if not owners:
                del self._shared[lo1]
                self._release_range(lo1, 1)
            return
        if owner not in self._exclusive:
            raise KeyError(f"owner {owner!r} holds no lease")
        lo, width = self._exclusive.pop(owner)
        self.frees_total += 1
        self._release_range(lo, width)

    def _release_range(self, lo: int, width: int) -> None:
        if any(d in self._lost for d in range(lo, lo + width)):
            # quarantine: only the healthy survivors of the range come
            # back, device by device (they coalesce with whatever
            # healthy neighborhood exists)
            for d in range(lo, lo + width):
                if d not in self._lost:
                    self._coalesce(d, 1)
            return
        self._coalesce(lo, width)

    def _coalesce(self, lo: int, size: int) -> None:
        while size < self.n_devices:
            buddy = lo ^ size
            free_list = self._free.get(size, [])
            if buddy not in free_list:
                break
            free_list.remove(buddy)
            lo = min(lo, buddy)
            size *= 2
            self.coalesces_total += 1
        self._free.setdefault(size, []).append(lo)

    # ----------------------------------------------------- device health
    def mark_lost(self, devices) -> list[str]:
        """Hard device loss: shrink capacity and quarantine. Free blocks
        containing a lost device are split down and the dead device
        removed; leased blocks stay leased (the scheduler reaps those
        leases and the quarantine happens at free time). Returns the
        owners whose lease touches a lost device — every one of them
        must be re-placed."""
        affected: set[str] = set()
        for d in sorted({int(x) for x in devices}):
            if d < 0 or d >= self.n_devices or d in self._lost:
                continue
            self._lost.add(d)
            self._degraded.discard(d)
            self.devices_lost_total += 1
            blk = self._free_block_containing(d)
            if blk is not None:
                lo, size = blk
                self._free[size].remove(lo)
                # split down to isolate d; re-add the healthy siblings
                while size > 1:
                    size //= 2
                    if d < lo + size:
                        self._free.setdefault(size, []).append(lo + size)
                    else:
                        self._free.setdefault(size, []).append(lo)
                        lo += size
                continue  # d itself (width 1) is quarantined: not re-added
            for owner, (lo, width) in self._exclusive.items():
                if lo <= d < lo + width:
                    affected.add(owner)
            owners = self._shared.get(d)
            if owners:
                affected.update(owners)
        return sorted(affected)

    def mark_host_lost(self, host: int) -> list[str]:
        """Whole-host loss: quarantine the host's entire device segment
        in one step. Returns every owner whose lease touches the dead
        host — each must be reaped and re-placed (a host-confined lease
        dies with its host; an explicitly multi-host lease dies when ANY
        of its hosts does). Counted separately from plain device loss
        (``hosts_lost_total``) so the fleet dashboard distinguishes a
        flaky chip from a dead machine."""
        host = int(host)
        if host < 0 or host >= self.n_hosts:
            raise ValueError(f"host {host} out of range "
                             f"(0..{self.n_hosts - 1})")
        lo = host * self.devices_per_host
        segment = range(lo, lo + self.devices_per_host)
        already = all(d in self._lost for d in segment)
        affected = self.mark_lost(segment)
        if not already and host not in self._lost_hosts:
            self._lost_hosts.add(host)
            self.hosts_lost_total += 1
        return affected

    def mark_degraded(self, devices) -> None:
        """Cordon: no NEW placements on these devices; existing leases
        drain naturally (the soft half of device loss)."""
        for d in devices:
            d = int(d)
            if 0 <= d < self.n_devices and d not in self._lost:
                self._degraded.add(d)

    def restore(self, devices) -> None:
        """Bring devices back (repaired / re-attached): lost ones
        re-enter the free pool and coalesce, degraded cordons lift."""
        for d in sorted({int(x) for x in devices}):
            if d < 0 or d >= self.n_devices:
                continue
            self._degraded.discard(d)
            if d in self._lost:
                self._lost.remove(d)
                self._coalesce(d, 1)
        self._lost_hosts = {
            h for h in self._lost_hosts
            if any(d in self._lost
                   for d in range(h * self.devices_per_host,
                                  (h + 1) * self.devices_per_host))
        }

    def _free_block_containing(self, d: int) -> tuple[int, int] | None:
        for size, los in self._free.items():
            for lo in los:
                if lo <= d < lo + size:
                    return (lo, size)
        return None

    # ------------------------------------------------------------ views
    def healthy_count(self) -> int:
        return self.n_devices - len(self._lost)

    def free_device_count(self) -> int:
        return sum(size * len(los) for size, los in self._free.items())

    def lease_of(self, owner: str) -> tuple[int, int] | None:
        """(lo, width) of ``owner``'s lease, shared blocks width 1."""
        owner = str(owner)
        if owner in self._owner_shared:
            return (self._owner_shared[owner], 1)
        return self._exclusive.get(owner)

    def widest_free(self) -> int:
        """Largest sub-mesh allocatable RIGHT NOW (0 = pool exhausted)."""
        widths = [
            size for size, los in self._free.items()
            if any(not self._range_degraded(lo, size) for lo in los)
        ]
        return max(widths, default=0)

    def stats(self) -> dict:
        free_devices = self.free_device_count()
        return {
            "n_devices": self.n_devices,
            "packing": self.packing,
            "n_hosts": self.n_hosts,
            "devices_per_host": self.devices_per_host,
            "healthy_devices": self.healthy_count(),
            "lost_devices": sorted(self._lost),
            "lost_hosts": sorted(self._lost_hosts),
            "degraded_devices": sorted(self._degraded),
            "free_devices": free_devices,
            "widest_free": self.widest_free(),
            "free_blocks": {
                size: sorted(los)
                for size, los in sorted(self._free.items()) if los
            },
            "exclusive_leases": {
                owner: {"lo": lo, "width": w}
                for owner, (lo, w) in sorted(self._exclusive.items())
            },
            "shared_blocks": {
                lo: sorted(owners)
                for lo, owners in sorted(self._shared.items())
            },
            "allocs_total": self.allocs_total,
            "frees_total": self.frees_total,
            "coalesces_total": self.coalesces_total,
            "devices_lost_total": self.devices_lost_total,
            "hosts_lost_total": self.hosts_lost_total,
        }

    def check_invariants(self) -> list[str]:
        """Recompute the device partition from scratch; returns every
        violation found (leaked, overlapping or double-booked ranges).
        Empty list == the allocator's books balance exactly."""
        problems: list[str] = []
        seen: dict[int, str] = {}

        def claim(d: int, what: str) -> None:
            if d in seen:
                problems.append(
                    f"device {d} double-booked: {seen[d]} and {what}")
            seen[d] = what

        for size, los in self._free.items():
            if not _is_pow2(size):
                problems.append(f"non-power-of-two free size {size}")
            for lo in los:
                if lo % size:
                    problems.append(f"misaligned free block ({lo},{size})")
                for d in range(lo, lo + size):
                    claim(d, f"free[{lo},{size})")
        for owner, (lo, width) in self._exclusive.items():
            if lo % width:
                problems.append(
                    f"misaligned lease {owner}=({lo},{width})")
            if width <= self.devices_per_host and \
                    self.host_of(lo) != self.host_of(lo + width - 1):
                problems.append(
                    f"host-confinable lease {owner}=({lo},{width}) "
                    f"straddles hosts {self.host_of(lo)} and "
                    f"{self.host_of(lo + width - 1)}")
            for d in range(lo, lo + width):
                claim(d, f"lease:{owner}")
        for lo, owners in self._shared.items():
            if not owners:
                problems.append(f"empty shared block at {lo}")
            if len(owners) > self.packing:
                problems.append(
                    f"shared block {lo} overpacked: {sorted(owners)}")
            claim(lo, f"shared:{sorted(owners)}")
        for d in self._lost:
            claim(d, "lost")
        missing = [d for d in range(self.n_devices) if d not in seen]
        if missing:
            problems.append(f"leaked devices (in no range): {missing}")
        out_of_range = [d for d in seen if d < 0 or d >= self.n_devices]
        if out_of_range:
            problems.append(f"devices out of range: {sorted(out_of_range)}")
        return problems


def feasible_widths(sharded: int | None) -> list[int]:
    """Candidate sub-mesh widths for a tenant, widest first.

    ``sharded=n``: every power-of-two divisor of ``n`` (the kernel's
    width-independence contract — ``n`` shards run bit-identically at
    any divisor width, down to virtual shards on one device).
    Unsharded: width 1 only."""
    if not sharded or int(sharded) <= 1:
        return [1]
    n = int(sharded)
    if not _is_pow2(n):
        raise ValueError(f"sharded={n} must be a power of two")
    out = []
    w = n
    while w >= 1:
        out.append(w)
        w //= 2
    return out


def platform_device_count() -> int:
    """How many real devices this process sees (the serving pool's
    default width). The one sanctioned enumeration site (PLACE001)."""
    import jax

    return len(jax.devices())


def build_mesh(lo: int, width: int, axis_name: str = "particles"):
    """The jax Mesh over physical devices ``[lo, lo+width)`` — or None
    when the lease is logical-only (width 1, or a pool wider than the
    platform: the tenant then runs its shards VIRTUALLY on one device,
    bit-identical by the kernel contract). The one sanctioned Mesh
    construction site in the serving layer (PLACE001)."""
    if int(width) <= 1:
        return None
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if int(lo) + int(width) > len(devs):
        return None
    # abc-lint: disable=SYNC001 np.asarray reshapes the host-side Device LIST for Mesh; no array leaves a device
    return Mesh(np.asarray(devs[int(lo):int(lo) + int(width)]),
                axis_names=(axis_name,))
