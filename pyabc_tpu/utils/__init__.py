"""Shared small utilities."""
from __future__ import annotations


def pow2_bucket(n: int, lo: int = 64, hi: int | None = None) -> int:
    """Round n up to a power-of-two bucket in [lo, hi].

    Static-shape XLA programs are compiled per shape; quantizing batch and
    capacity dimensions to power-of-two buckets bounds the number of distinct
    compilations over a run.
    """
    b = lo
    while b < n and (hi is None or b < hi):
        b *= 2
    return b if hi is None else min(b, hi)
