"""Fork-safety guard for host proposal closures.

The multicore samplers distribute work by forking a parent whose JAX/XLA
backend is already initialized (reference parity:
``pyabc/sampler/multicorebase.py`` forks the same way over a torch-free
parent). Forking a process with live XLA threads is safe ONLY as long as
the child never touches the backend — which is why the whole host proposal
path (`inference/util.py` host section) is written JAX-free: numpy/scipy
draws, pandas transitions, float math.

That invariant is one stray captured ``jax.Array`` away from the round-1
multicore deadlock (a child touching a forked XLA backend hangs in its
mutex). This module makes the invariant *checkable*: `find_jax_refs`
recursively walks a closure — cells, function defaults, instance
attributes, containers — and returns the access paths of any object whose
type lives in ``jax``/``jaxlib``. The multicore samplers run the check once
per generation before forking, and fail fast with the offending path
instead of deadlocking silently.
"""
from __future__ import annotations

from dataclasses import fields, is_dataclass

#: module prefixes whose instances must not be captured by a forked closure
_BANNED_PREFIXES = ("jax", "jaxlib")

#: walk at most this deep; the proposal closure graph is shallow (closure ->
#: strategy objects -> numpy/scipy state)
_MAX_DEPTH = 8


def _is_banned(obj) -> bool:
    mod = type(obj).__module__ or ""
    return mod == "jax" or any(
        mod.startswith(p + ".") or mod == p for p in _BANNED_PREFIXES
    )


def _children(obj):
    """(label, child) pairs to recurse into. Deliberately NOT __globals__:
    module namespaces legitimately import jax for the device path; the
    hazard is jax OBJECTS reachable from the closure's data graph."""
    if isinstance(obj, dict):
        for k, v in obj.items():
            yield f"[{k!r}]", v
        return
    if isinstance(obj, (list, tuple, set, frozenset)):
        for i, v in enumerate(obj):
            yield f"[{i}]", v
        return
    if callable(obj):
        closure = getattr(obj, "__closure__", None)
        names = getattr(getattr(obj, "__code__", None), "co_freevars", ())
        for name, cell in zip(names, closure or ()):
            try:
                yield f".<cell {name}>", cell.cell_contents
            except ValueError:  # pragma: no cover - empty cell
                continue
        for i, d in enumerate(getattr(obj, "__defaults__", None) or ()):
            yield f".<default {i}>", d
        self_obj = getattr(obj, "__self__", None)
        if self_obj is not None:
            yield ".__self__", self_obj
    if is_dataclass(obj) and not isinstance(obj, type):
        for f in fields(obj):
            yield f".{f.name}", getattr(obj, f.name, None)
        return
    d = getattr(obj, "__dict__", None)
    if isinstance(d, dict):
        for k, v in d.items():
            yield f".{k}", v


def find_jax_refs(root, max_depth: int = _MAX_DEPTH) -> list[str]:
    """Access paths of jax/jaxlib-typed objects reachable from ``root``."""
    found: list[str] = []
    seen: set[int] = set()
    stack = [("<root>", root, 0)]
    while stack:
        path, obj, depth = stack.pop()
        if obj is None or isinstance(obj, (str, bytes, int, float, bool)):
            continue
        if id(obj) in seen:
            continue
        seen.add(id(obj))
        if isinstance(obj, type) or type(obj).__name__ == "module":
            continue  # classes/modules referencing jax are fine; values not
        if _is_banned(obj):
            found.append(f"{path}: {type(obj).__module__}."
                         f"{type(obj).__qualname__}")
            continue
        if depth >= max_depth:
            continue
        for label, child in _children(obj):
            stack.append((path + label, child, depth + 1))
    return found


def assert_fork_safe(simulate_one) -> None:
    """Raise with the offending paths if the closure captures jax state."""
    refs = find_jax_refs(simulate_one)
    if refs:
        raise RuntimeError(
            "proposal closure captures JAX state and cannot be forked into "
            "multiprocess workers (a child touching a forked XLA backend "
            "deadlocks). Offending references:\n  " + "\n  ".join(refs)
            + "\nUse BatchedSampler for device models, or a spawn-context "
            "sampler (start_method='spawn')."
        )
