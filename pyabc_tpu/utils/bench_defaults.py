"""Single source of truth for the benchmark's default configuration.

Both the repo-root ``bench.py`` harness and the ``abc-bench`` CLI fallback
(used for wheel installs without the repo checkout) read these constants,
so the two entry points cannot drift apart (round-2 advisor finding: the
CLI re-hardcoded the generation count by hand).

Sizing rationale lives with the numbers:
- ``(DEFAULT_GENS + 1)`` must be a multiple of ``DEFAULT_G`` so no stub
  tail chunk is scheduled; 31 with G=16 gives chunks t=1..16 and 17..32,
  staying just clear of the deep-schedule acceptance collapse
  (MedianEpsilon at the noise floor, t >~ 33).
- G=16 beats G=8 by halving per-generation sync cost over the tunnel
  (measured round 3: 83k vs 45k pps); G=20+ overruns the floor.
"""

DEFAULT_POP = 1000
DEFAULT_GENS = 31
DEFAULT_G = 16
DEFAULT_BUDGET_S = 300.0
