"""Single source of truth for the benchmark's default configuration.

Both the repo-root ``bench.py`` harness and the ``abc-bench`` CLI fallback
(used for wheel installs without the repo checkout) read these constants,
so the two entry points cannot drift apart (round-2 advisor finding: the
CLI re-hardcoded the generation count by hand).

Sizing rationale lives with the numbers:
- ``(DEFAULT_GENS + 2)`` must be a multiple of ``DEFAULT_G`` so no stub
  tail chunk is scheduled: since round 5 generation 0 rides the FIRST
  chunk (prior-mode first generation), so a run is exactly
  ``(GENS + 2) / G`` full chunks — 30 with G=8 gives chunks t=0..7,
  8..15, 16..23, 24..31, staying just clear of the deep-schedule
  acceptance collapse (MedianEpsilon at the noise floor, t >~ 33).
- Round 3 (synchronous per-chunk fetch): G=16 beat G=8 (83k vs 45k pps)
  by halving the per-generation share of the ~0.1s tunnel sync. Round 4's
  THREADED fetch pipeline (ABCSMC fetch_pipeline_depth) hides that
  latency behind later chunks' compute, flipping the tradeoff: shorter
  chunks expose less latency per fetch and yield more true steady-state
  windows per run (measured round 4: G=8 mid-run chunks 0.026-0.028 s =
  ~290k pps vs ~120k at G=16).
"""

DEFAULT_POP = 1000
DEFAULT_GENS = 30
DEFAULT_G = 8
DEFAULT_BUDGET_S = 300.0
# wall-window width for the strict global-clock median (a few chunk
# periods, so one congested window can't dominate and clustered
# completions average out)
DEFAULT_WINDOW_S = 2.0
# scale lane (round 7): the r5 measured scale gap was pop-16384 fused LV
# with LocalTransition(k_fraction=0.25) at 800-4000 pps; the lane
# reproduces exactly that statistical config. 12 generations = one
# 16k-row calibration + enough post-fill chunks for a span basis while
# leaving most of the lane budget to steady generations.
DEFAULT_SCALE_POP = 16384
DEFAULT_SCALE_GENS = 12
# elastic lane (round 8): worker-tracing attribution on the broker path.
# A host-model gauss config — the lane measures ATTRIBUTION (worker
# compute / serialization / broker RTT / queue wait / orchestrator poll
# fracs and the >=0.9 attributed-fraction guard), not throughput, so it
# runs fine on the CPU probe. 2 workers x 3 generations x pop 120 with a
# 2 ms simulate keeps a warm run a few seconds; run 0 is warm-up (worker
# process startup + first connects), runs >= 1 are the guarded ones.
DEFAULT_ELASTIC_POP = 120
DEFAULT_ELASTIC_GENS = 3
DEFAULT_ELASTIC_WORKERS = 2
DEFAULT_ELASTIC_RUNS = 3
DEFAULT_ELASTIC_SIM_DELAY_S = 0.002
#: regression guard: minimum attributed fraction of a warm elastic run's
#: wall clock (ISSUE round 8 acceptance: >= 0.9 on the CPU probe)
ELASTIC_ATTRIBUTED_FRAC_MIN = 0.9
# resilience lane (round 9): self-healing under injected faults. Same
# CPU-capable gauss config as the elastic lane, but one of the two
# workers is MORTAL — its fault plan kills it hard after a few batches
# every life and a babysitter respawns it — so every generation sees at
# least one mid-batch death, a lease requeue and a redispatch to the
# surviving worker. The lane guards (a) run completion WITHOUT
# TimeoutError, (b) >= 1 redispatched batch, (c) the warm-run attributed
# fraction >= 0.9 with recovery windows counted via the gap accountant's
# `recovery` category. Lease timeout is tightened so recovery latency
# (not the 15 s production backstop) fits a CI-scale run.
DEFAULT_RESILIENCE_POP = 100
DEFAULT_RESILIENCE_GENS = 3
DEFAULT_RESILIENCE_RUNS = 2
DEFAULT_RESILIENCE_SIM_DELAY_S = 0.002
DEFAULT_RESILIENCE_KILL_AFTER_BATCHES = 3
DEFAULT_RESILIENCE_LEASE_TIMEOUT_S = 1.0
#: regression guard: minimum attributed fraction of a warm resilience
#: run's wall clock (round 9 acceptance: >= 0.9 under injected faults)
RESILIENCE_ATTRIBUTED_FRAC_MIN = 0.9
#: round-10 satellite guard: fraction of the broker-logged
#: orphaned->redispatch stall wall clock that may remain UNCOVERED by
#: recovery spans (plus a small absolute floor for clock granularity) —
#: recovery-accounting regressions fail the lane instead of passing
#: silently as slightly-darker dark time
RESILIENCE_RECOVERY_UNATTRIBUTED_FRAC_MAX = 0.1
RESILIENCE_RECOVERY_UNATTRIBUTED_ABS_S = 0.1
# health lane (round 10): in-kernel health guards + RunSupervisor
# recovery, measured end-to-end on a CPU-capable fused gauss config. A
# seed-matched fault-free reference run and a NaN-poisoned run (one
# `device.carry:nan_poison` injection on the second chunk's carry) must
# produce BIT-IDENTICAL trajectories — the rollback target is exactly
# the state the clean run chained from — with at most
# HEALTH_MAX_ROLLBACKS rolled-back chunks, and the health detection must
# add zero blocking syncs (SyncLedger counts equal between the runs).
DEFAULT_HEALTH_POP = 100
DEFAULT_HEALTH_GENS = 8
DEFAULT_HEALTH_G = 4
HEALTH_MAX_ROLLBACKS = 1
# dispatch lane (round 12): the single async dispatch engine measured on
# BOTH bases of the SAME runs — strict wall clock (run start to last
# persist, everything included) vs pipeline-full span (post-fill chunks
# only) — so the dual-basis gap the engine exists to close is a guarded
# ratio, not a narrative. CPU-capable fused gauss config; also exercises
# a mid-schedule minimum_epsilon stop so >= 1 speculative chunk is
# rolled back (bit-identity of that rollback is tier-1-tested in
# tests/test_dispatch.py; the lane guards it stays EXERCISED).
DEFAULT_DISPATCH_POP = 300
DEFAULT_DISPATCH_GENS = 12
DEFAULT_DISPATCH_G = 2
DEFAULT_DISPATCH_RUNS = 3
#: regression guard: strict wall-clock pps of a warm run must stay
#: within this factor of the same run's pipeline-full span pps
#: (ISSUE round 12 acceptance: within 1.5x)
DISPATCH_WALL_TO_PIPELINE_MIN = 1.0 / 1.5
# mesh lane (round 13): sharded fused sampling on the device mesh — the
# MULTICHIP dryrun's shapes (pop 1024, G=8) promoted to a measured
# first-class path. gens = 9 => gen 0 + exactly one full G=8 chunk per
# run, the minimum that exercises the chunk-boundary merge + a second
# (short) chunk's dispatch. The lane runs in a subprocess (forced 8
# virtual CPU devices without an accelerator) under its own budget.
DEFAULT_MESH_POP = 1024
DEFAULT_MESH_G = 8
DEFAULT_MESH_GENS = 9
DEFAULT_MESH_BUDGET_S = 120.0
# multihost leg (round 21): 2 gloo processes × 4 forced CPU devices
# timeshare one core with a python interpreter each, so the leg runs a
# deliberately small config — the guards are bit-identity + sync
# budget, not throughput (gloo-over-loopback pps is recorded as a
# proxy only)
DEFAULT_MESH_MH_POP = 128
DEFAULT_MESH_MH_GENS = 4
# serve lane (round 15): mesh-aware serving on a forced-8-device pool —
# a mixed fleet (one sharded=4 big tenant on a width-4 sub-mesh lease +
# unsharded width-1 tenants) through one checkpoint-preemption and one
# injected device_lost (6 of 8 devices), every posterior bit-identical
# to its solo run. Small-tenant shapes sized so a warm run is ~1 s on
# the 1-core box; the big tenant is long enough (gens x pop) that both
# events land mid-run instead of racing its completion. (The round-14
# chaos-ISOLATION guard lives in tier-1: tests/test_serving.py.)
DEFAULT_SERVE_TENANTS = 4
DEFAULT_SERVE_POP = 300
DEFAULT_SERVE_GENS = 6
DEFAULT_SERVE_BIG_POP = 800
DEFAULT_SERVE_BIG_GENS = 16
DEFAULT_SERVE_BUDGET_S = 300.0
#: fairness guard: max/min per-tenant accepted-pps ratio across the
#: fault-free fleet (equal shapes through equal capacity; the bound is
#: generous because overlap on a 1-core box is scheduler luck)
SERVE_FAIRNESS_MAX_RATIO = 3.0
# storage lane (round 17): History ingest — the same pop-16384
# packed-fetch generations appended to the row store (WAL on/off) and
# the columnar generation-batch store. pop matches the scale lane's
# headline population (the ISSUE acceptance scale); 6 generations keep
# the row-store leg a few seconds on the 1-core box while amortizing
# per-append setup; the guard is the tentpole's >=10x acceptance line.
DEFAULT_STORAGE_POP = 16384
DEFAULT_STORAGE_GENS = 6
DEFAULT_STORAGE_GUARD_MIN_X = 10.0
# scenario lane (round 18): segmented early-reject on the scenario zoo.
# Gillespie birth-death is the headline: one deep MedianEpsilon run per
# mode (ON/OFF) at a population whose pow2 lane batch keeps the CPU
# proxy inside the lane budget (accelerators lift via
# PYABC_TPU_BENCH_SCENARIO_POP); generations run DEEP so the run enters
# the few-percent-acceptance regime the tentpole targets — the late
# window (acceptance <= SCENARIO_LATE_ACC) is where the provable-reject
# fraction, and therefore the speedup, lives. 10 segments of a 200-leap
# trajectory bound the retire granularity at 10% of the sim cost.
DEFAULT_SCENARIO_POP = 1024
DEFAULT_SCENARIO_POP_TPU = 131072
DEFAULT_SCENARIO_GENS = 12
DEFAULT_SCENARIO_SEGS = 10
# the late window: chunks FULLY at/below this acceptance — the
# few-percent regime where most proposals are provably rejectable
SCENARIO_LATE_ACC = 0.01
#: regression guard: late-window accepted-pps ratio ON/OFF (the ISSUE 15
#: acceptance line; armed only when the run reaches the late window)
SCENARIO_SPEEDUP_MIN_X = 2.0
# sharded scenario leg (ISSUE 17): the composed sharded+segmented
# kernel measured on a forced-8-device mesh in a subprocess. The
# CPU-proxy guard is looser than the unsharded one — 8 virtual devices
# timeshare one core, so the retire win competes with collective
# overhead the real chip does not pay (>=2x stays the real-TPU target).
SCENARIO_SHARDED_SPEEDUP_MIN_X = 1.5
# the r20 capture measured the whole leg at ~333 s on the 1-core box
# (6 drained runs); the budget leaves headroom so the warm full runs
# (max_walltime = 0.3 * budget each) never get cut mid-measurement
DEFAULT_SCENARIO_SHARDED_BUDGET_S = 600.0
# the sharded leg runs 6 drained runs (2 cold + 4 warm) on 8 virtual
# devices timesharing one core — half the inline lane's pop and one
# fewer generation keep the whole leg inside its budget there
DEFAULT_SCENARIO_SHARDED_POP = 512
DEFAULT_SCENARIO_SHARDED_GENS = 11
# traffic lane (round 19): fleet-scale churn — an open-loop seeded
# Poisson arrival process from the spec zoo against a live RunScheduler
# on forced-8-device CPU. 1000 tenants is the ISSUE acceptance scale
# (env-overridable; CI runs the ~40-tenant smoke profile in its own
# job). The arrival rate is deliberately above what a 1-core box can
# drain so the lane spends its budget in the 429/retry/GC regime;
# retention keep-last-1 + the fleet disk budget make "bounded disk
# under churn" an assertable number instead of a hope.
DEFAULT_TRAFFIC_TENANTS = 1000
DEFAULT_TRAFFIC_SMOKE_TENANTS = 40
DEFAULT_TRAFFIC_RATE_HZ = 8.0
DEFAULT_TRAFFIC_BUDGET_S = 480.0
DEFAULT_TRAFFIC_SMOKE_BUDGET_S = 60.0
DEFAULT_TRAFFIC_PROFILE = "full"
DEFAULT_TRAFFIC_SEED = 190
#: fleet disk budget for the lane's RetentionPolicy: total History
#: bytes (db + columnar + archives) the sweep must keep the fleet under
DEFAULT_TRAFFIC_DISK_BUDGET_BYTES = 256 * 1024 * 1024
#: p99 bound on wall time spent inside submit() (scheduler lock health
#: under churn; generous — the 1-core box runs orchestrators, the pump
#: and the generator on one core)
TRAFFIC_ADMIT_P99_MAX_S = 2.0
#: Retry-After honesty: p90 of observed_wait / first_hint. An honest
#: hint lands near 1; the bound is loose because open-loop pressure
#: keeps REFILLING the queue between a rejection and its retry, which
#: legitimately stretches the observed wait beyond any single hint.
TRAFFIC_HONESTY_P90_MAX = 10.0
#: within-class fairness: max/min accepted-pps across completed tenants
#: of the SAME traffic class (the serve-lane bound, fleet-sized)
TRAFFIC_FAIRNESS_MAX_RATIO = 3.0
#: round 22, the traffic lane's `slo` leg -------------------------------
#: floor on warm-run attributed wall-clock fraction with the per-tenant
#: flight recorder ARMED (tracer + metrics + snapshot-on-demand): the
#: recorder's ring writes ride existing instrumentation, so steady-state
#: coverage must match the resilience lane's bar — a recorder that
#: stalls the run would show up here as dark time
SLO_RECORDER_ATTRIBUTED_FRAC_MIN = 0.9
#: ceiling on fast-burn alert latency in INJECTED-clock seconds for a
#: total (100% failure) outage striking a warmed-up (1h of good
#: traffic) service: the page needs BOTH fast windows past 14.4x, and
#: the 1h window is the slower gate — it must accumulate a 14.4% bad
#: fraction, ~0.144 * 3600 ~= 518 s of outage, plus sampling slack
SLO_ALERT_LATENCY_MAX_S = 600.0
