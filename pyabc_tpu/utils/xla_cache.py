"""Compiled-program caching: the persistent XLA disk cache, and the
in-process shape-keyed kernel cache the serving layer shares.

Two layers with different hit costs:

- :func:`setup_xla_cache` — JAX's persistent compilation cache on disk.
  The fused multi-generation programs cost ~15-25 s of XLA compile
  each; the disk cache deserializes them in ~1 s. One helper so the
  policy (default directory, min-compile-time threshold, env-var export
  for subprocess inheritance) cannot drift between `bench.py`,
  `tests/conftest.py` and `__graft_entry__.py`.
- :class:`KernelCache` — round 14 (multi-tenant serving): live
  ``DeviceContext`` objects keyed by PROGRAM SHAPE (models, population,
  fused G, distance/acceptor/transition config, observed-data digest).
  A tenant whose shape was already served adopts the cached context's
  jitted kernels (`ABCSMC.adopt_device_context` semantics) and pays
  ZERO compile — not even the ~1 s disk-cache deserialize — which is
  what makes admission of the millionth identical workload cheap.
"""
from __future__ import annotations

import os
import threading
from collections import OrderedDict


def setup_xla_cache(default_dir: str, *, export_env: bool = False) -> str | None:
    """Point JAX's persistent compilation cache at ``default_dir`` (the
    ``JAX_COMPILATION_CACHE_DIR`` env var wins when set). ``export_env``
    additionally writes the env var so subprocesses inherit the cache.
    Best-effort: a failure degrades to uncached compilation, never an
    error. Returns the cache dir in use (None on failure)."""
    try:
        import jax

        cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR", default_dir)
        os.makedirs(cache_dir, exist_ok=True)
        if export_env:
            os.environ["JAX_COMPILATION_CACHE_DIR"] = cache_dir
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        return cache_dir
    except Exception as e:
        # best-effort, but VISIBLY so: without the cache every fused
        # program pays its full 15-25 s compile and the reason would
        # otherwise be undiscoverable
        import warnings

        warnings.warn(
            f"persistent XLA compile cache disabled ({e!r}); "
            f"fused programs will recompile every run", stacklevel=2,
        )
        return None


def _model_identity(model) -> str:
    """A model's contribution to the program-shape key: the content
    hash of its traced closure when it has one (JaxModel), else repr —
    the NAME alone is not identity, two builders can both say "gauss"
    while closing over different constants."""
    content_hash = getattr(model, "content_hash", None)
    if callable(content_hash):
        return content_hash()
    return f"{type(model).__name__}:{model!r}"


def _component_config(obj) -> str:
    """Config digest of a distance/acceptor/eps/transition component:
    its ``get_config()`` when implemented, else the construction-time
    scalar attributes — type names alone miss e.g. ``PNormDistance(p=1)``
    vs ``p=2``, which trace to different kernels."""
    import json

    cfg = None
    get_config = getattr(obj, "get_config", None)
    if callable(get_config):
        try:
            cfg = get_config()
        except Exception:
            cfg = None
    if cfg is None:
        cfg = {
            k: v for k, v in sorted(vars(obj).items())
            if not k.startswith("_")
            and isinstance(v, (bool, int, float, str, type(None)))
        }
    return f"{type(obj).__name__}:" + json.dumps(
        cfg, sort_keys=True, default=repr)


def program_shape_key(abc) -> tuple:
    """The program-shape identity of a prepared ABCSMC run.

    Two runs with equal keys trace to the SAME jitted device programs
    (and close over the same observed data), so adopting one's
    ``DeviceContext`` into the other skips trace+compile entirely.
    Everything the compiled kernels close over or specialize on is in
    the key: model CONTENT hashes (the traced simulator's code +
    closure constants, not just its display name), model count and
    prior weights, per-model parameter priors, population schedule,
    fused chunk length, fetch dtype, distance/acceptor/eps/transition
    CONFIGS (not just type names — ``PNormDistance(p=1)`` and ``p=2``
    are different programs) and the flattened observed-data bytes
    (kernels close over ``x_0``; adoption refuses mismatched
    observations, so the digest gates lookup too). The run seed is
    deliberately ABSENT — RNG keys are array arguments, one compiled
    program serves every seed.

    Requires ``abc.new(...)``/``abc.load(...)`` to have run (the spec
    exists); raises otherwise so a half-built run cannot poison the
    cache with an underspecified key.
    """
    import hashlib
    import json

    import numpy as np

    if abc.spec is None:
        raise ValueError(
            "program_shape_key needs a prepared run: call .new()/.load() "
            "(the observed-data spec is part of the program shape)"
        )
    x0 = np.ascontiguousarray(
        np.asarray(abc.spec.flatten_host(abc.x_0), np.float32))
    prior_probs = np.ascontiguousarray(
        np.asarray(abc.model_prior_probs, np.float64))
    # placement identity (mesh-aware serving): a sharded program and
    # its unsharded twin are DIFFERENT compiled kernels, and a shard_map
    # program is pinned to its mesh's physical devices — adopting a
    # context compiled for another sub-mesh would dispatch onto devices
    # the tenant does not lease
    mesh = getattr(abc, "mesh", None)
    mesh_sig = (None if mesh is None
                else tuple(int(d.id) for d in mesh.devices.flat))
    shard_sig = repr(getattr(abc, "sharded", None))
    return (
        tuple(_model_identity(m) for m in abc.models),
        int(abc.K),
        hashlib.sha256(prior_probs.tobytes()).hexdigest(),
        tuple(repr(p) for p in abc.parameter_priors),
        json.dumps(abc.population_strategy.get_config(), sort_keys=True,
                   default=str),
        int(abc.fused_generations),
        str(abc.fetch_dtype),
        _component_config(abc.distance_function),
        _component_config(abc.acceptor),
        _component_config(abc.eps),
        tuple(_component_config(tr) for tr in abc.transitions),
        int(abc.spec.total_size),
        hashlib.sha256(x0.tobytes()).hexdigest(),
        mesh_sig,
        shard_sig,
    )


class KernelCache:
    """Shape-keyed live ``DeviceContext`` cache (multi-tenant serving).

    ``adopt_or_register(abc)`` is the whole API: on a HIT the cached
    context's compiled kernels are adopted into ``abc`` (tenant k+1
    with a seen shape pays zero compile); on a MISS the tenant's own
    context is registered after it exists (:meth:`register_from`). LRU
    over ``max_entries`` bounds device/host memory held by cached
    programs. Thread-safe — admission and tenant orchestrator threads
    race on it by design.
    """

    def __init__(self, max_entries: int = 8):
        self.max_entries = max(int(max_entries), 1)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, object]" = OrderedDict()  # abc-lint: guarded-by=_lock
        self.hits = 0
        self.misses = 0

    def adopt_or_register(self, abc) -> bool:
        """Adopt cached kernels into ``abc`` if its shape was seen.

        Returns True on a cache hit (kernels adopted — ``abc`` will not
        compile), False on a miss (call :meth:`register_from` once the
        run has built its context). A cached context that fails
        adoption (defensive: the key should preclude it) is evicted and
        counted a miss, never an error.
        """
        if not getattr(abc, "_device_capable", False):
            return False  # host path: nothing compiled to share
        # pin the PRE-RUN key on the instance: register_from() runs
        # after the run, when adaptive components may have refit state —
        # recomputing there could register under a key no future
        # lookup (always pre-run) would ever produce
        key = abc._program_shape_key = program_shape_key(abc)
        with self._lock:
            ctx = self._entries.get(key)
            if ctx is not None:
                self._entries.move_to_end(key)
        if ctx is not None:
            try:
                abc._adopt_device_context_inner(ctx)
            except Exception:
                with self._lock:
                    self._entries.pop(key, None)
                    self.misses += 1
                return False
            with self._lock:
                self.hits += 1
            return True
        with self._lock:
            self.misses += 1
        return False

    def register_from(self, abc) -> bool:
        """Offer ``abc``'s built device context for future same-shape
        runs; returns True if it was (newly) cached."""
        ctx = abc._device_ctx
        if ctx is None:
            return False
        key = getattr(abc, "_program_shape_key", None)
        if key is None:
            key = program_shape_key(abc)
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                return False
            self._entries[key] = ctx
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
            return True

    def stats(self) -> dict:
        with self._lock:
            n = len(self._entries)
            hits, misses = self.hits, self.misses
        total = hits + misses
        return {
            "entries": n, "hits": hits, "misses": misses,
            "hit_rate": round(hits / total, 4) if total else None,
        }
