"""Persistent XLA compilation-cache setup, shared by every entry point.

The fused multi-generation programs cost ~15-25 s of XLA compile each;
the cache deserializes them in ~1 s. One helper so the policy (default
directory, min-compile-time threshold, env-var export for subprocess
inheritance) cannot drift between `bench.py`, `tests/conftest.py` and
`__graft_entry__.py`.
"""
from __future__ import annotations

import os


def setup_xla_cache(default_dir: str, *, export_env: bool = False) -> str | None:
    """Point JAX's persistent compilation cache at ``default_dir`` (the
    ``JAX_COMPILATION_CACHE_DIR`` env var wins when set). ``export_env``
    additionally writes the env var so subprocesses inherit the cache.
    Best-effort: a failure degrades to uncached compilation, never an
    error. Returns the cache dir in use (None on failure)."""
    try:
        import jax

        cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR", default_dir)
        os.makedirs(cache_dir, exist_ok=True)
        if export_env:
            os.environ["JAX_COMPILATION_CACHE_DIR"] = cache_dir
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        return cache_dir
    except Exception as e:
        # best-effort, but VISIBLY so: without the cache every fused
        # program pays its full 15-25 s compile and the reason would
        # otherwise be undiscoverable
        import warnings

        warnings.warn(
            f"persistent XLA compile cache disabled ({e!r}); "
            f"fused programs will recompile every run", stacklevel=2,
        )
        return None
