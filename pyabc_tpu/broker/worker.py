"""The elastic worker: join anytime, pull slots, push particles, die freely.

Reference parity: ``pyabc/sampler/redis_eps/work.py::work`` + the
``abc-redis-worker`` CLI: a worker process polls the broker for the current
generation, evaluates ``simulate_one`` per handed-out slot, ships results
back in batches, and loops into the next generation. Death at ANY point is
safe (abandoned slots are provenance ids only); joining mid-generation is
the normal case. Per-worker CSV logging mirrors the reference worker's
runtime bookkeeping. SIGTERM/SIGINT are handled like the reference's
``KillHandler``: the current batch is finished and shipped, the worker
deregisters from the broker ("bye"), and the loop exits cleanly (exit 0) —
a cluster preemption never strands half-evaluated work.
"""
from __future__ import annotations

import csv
import os
import pickle
import signal
import socket
import threading
import time
import uuid

import numpy as np

from ..observability import SYSTEM_CLOCK
from .protocol import request


def run_worker(host: str, port: int, *, worker_id: str | None = None,
               poll_s: float = 0.3, max_generations: float = float("inf"),
               runtime_s: float = float("inf"),
               log_file: str | None = None,
               catch_exceptions: bool = True,
               seed: int | None = None,
               _stop_check=None) -> int:
    """Serve generations until the broker goes away / runtime ends.

    Returns the number of evaluations performed. Reconnects with backoff
    while the broker is unreachable (a worker may be started BEFORE the
    manager — reference semantics).

    ``catch_exceptions`` (reference ``abc-redis-worker --catch``): a
    raising ``simulate_one`` ships a rejected error-record particle and
    the loop continues — a deterministic model bug then surfaces in the
    orchestrator's error records instead of serially killing every
    worker in the pool. Disable to make model errors fatal (debugging).
    """
    addr = (host, int(port))
    wid = worker_id or f"{socket.gethostname()}-{os.getpid()}-{uuid.uuid4().hex[:6]}"
    # worker-unique numpy seed: host simulate_one draws via np.random.
    # ``seed`` (also via PYABC_TPU_WORKER_SEED) pins it for reproducible
    # tests; the default mixes the pid with os.urandom entropy — stronger
    # than the old pid+wallclock mix (two workers forked in the same
    # second shared most seed bits) and free of wall-clock reads
    env_seed = os.environ.get("PYABC_TPU_WORKER_SEED")
    if seed is None and env_seed is not None:
        seed = int(env_seed)
    if seed is None:
        seed = (os.getpid() * 1000003
                + int.from_bytes(os.urandom(4), "little"))
    np.random.seed(seed % (2**31 - 1))
    clock = SYSTEM_CLOCK
    t_end = clock.now() + runtime_s if np.isfinite(runtime_s) else None
    n_eval_total = 0
    gens_served = 0
    last_counted_gen = -1
    log_writer = None
    if log_file:
        fh = open(log_file, "a", newline="")
        log_writer = csv.writer(fh)
        if fh.tell() == 0:
            log_writer.writerow(
                ["worker_id", "generation", "t", "n_eval", "n_accepted",
                 "wall_s"])

    # reference KillHandler: SIGTERM/SIGINT request a GRACEFUL exit — the
    # in-progress batch still ships, then the worker deregisters. Handlers
    # can only be installed from the main thread (tests run workers in
    # threads; there the _stop_check hook covers shutdown instead).
    signaled = threading.Event()
    restore: dict = {}
    if threading.current_thread() is threading.main_thread():
        def _on_signal(signum, frame):
            signaled.set()

        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                restore[sig] = signal.signal(sig, _on_signal)
            except (ValueError, OSError):  # pragma: no cover - env-specific
                pass

    def stopping() -> bool:
        if signaled.is_set():
            return True
        return _stop_check() if _stop_check is not None else False

    try:
        while True:
            if stopping():
                break
            if t_end and clock.now() > t_end:
                break
            if gens_served >= max_generations:
                break
            try:
                reply = request(addr, ("hello", wid))
            except (ConnectionError, OSError):
                time.sleep(min(poll_s * 4, 2.0))
                continue
            if reply[0] != "work":
                time.sleep(poll_s)
                continue
            # NOTE: no served-generation memory on purpose — a transport
            # blip mid-generation must NOT bench the worker for the rest of
            # that generation; re-entering a still-running generation just
            # pulls more slots (a finished generation answers hello "wait")
            _, gen, t, payload, batch, mode = reply
            simulate_one = pickle.loads(payload)
            t0 = clock.now()
            n_eval = n_acc = 0
            while True:
                try:
                    r = request(addr, ("get_slots", wid, gen, batch))
                except (ConnectionError, OSError):
                    break  # broker gone; outer loop will reconnect
                if r[0] != "slots":
                    break
                _, start, stop = r
                triples = []
                aborted = False
                for slot in range(start, stop):
                    # dynamic: one evaluation per slot. static: a quota
                    # unit — evaluate until THIS unit accepts (reference
                    # RedisStaticSampler / MappingSampler semantics);
                    # rejects ship as records either way.
                    unit_evals = 0
                    while True:
                        try:
                            particle = simulate_one()
                        except Exception as e:
                            if not catch_exceptions:
                                raise
                            from ..core.population import Particle

                            particle = Particle(
                                m=-1, parameter={}, weight=0.0,
                                sum_stat={}, distance=float("inf"),
                                accepted=False, error=repr(e),
                            )
                        n_eval += 1
                        unit_evals += 1
                        accepted = bool(particle.accepted)
                        triples.append((
                            slot,
                            pickle.dumps(particle, pickle.HIGHEST_PROTOCOL),
                            accepted,
                        ))
                        if accepted:
                            n_acc += 1
                        if accepted or mode != "static":
                            break
                        if stopping():
                            # preemption mid-unit: ship what we have and
                            # exit — delay bounded by ONE simulate_one
                            aborted = True
                            break
                        if mode == "static" and len(triples) >= 64:
                            # a spinning static unit (collapsed acceptance
                            # or a deterministically-raising model under
                            # --catch) must not hoard its reject/error
                            # records unboundedly: flush them mid-unit so
                            # errors surface and memory stays bounded
                            try:
                                rf = request(addr,
                                             ("results", wid, gen, triples))
                            except (ConnectionError, OSError):
                                aborted = True
                                break
                            triples = []
                            if rf[0] != "ok":
                                aborted = True
                                break
                        if unit_evals % 256 == 0:
                            # liveness probe: a static unit can spin for
                            # thousands of evaluations at a collapsed
                            # acceptance rate; abandon it as soon as the
                            # broker finalized the generation (eval
                            # budget / another worker finished it)
                            try:
                                hb = request(addr,
                                             ("heartbeat", wid, gen))
                            except (ConnectionError, OSError):
                                aborted = True
                                break
                            if hb[0] != "ok":
                                aborted = True
                                break
                    if aborted or stopping():
                        aborted = True
                        break
                try:
                    r2 = request(addr, ("results", wid, gen, triples))
                except (ConnectionError, OSError):
                    break
                if r2[0] != "ok" or aborted or stopping():
                    # graceful shutdown: the batch above was flushed; stop
                    # pulling new slots
                    break
            if gen != last_counted_gen:
                gens_served += 1
                last_counted_gen = gen
            n_eval_total += n_eval
            if n_eval == 0 and not stopping():
                # nothing handed out (generation ending / transport blip):
                # don't hot-spin on hello
                time.sleep(poll_s)
            if log_writer is not None:
                log_writer.writerow(
                    [wid, gen, t, n_eval, n_acc,
                     round(clock.now() - t0, 3)])
                fh.flush()
    finally:
        # deregister so manager status doesn't show ghost workers
        try:
            request(addr, ("bye", wid))
        except (ConnectionError, OSError):
            pass
        for sig, old in restore.items():
            try:
                signal.signal(sig, old)
            except (ValueError, OSError):  # pragma: no cover
                pass
    return n_eval_total
