"""The elastic worker: join anytime, pull slots, push particles, die freely.

Reference parity: ``pyabc/sampler/redis_eps/work.py::work`` + the
``abc-redis-worker`` CLI: a worker process polls the broker for the current
generation, evaluates ``simulate_one`` per handed-out slot, ships results
back in batches, and loops into the next generation. Death at ANY point is
safe (abandoned slots are provenance ids only); joining mid-generation is
the normal case. Per-worker CSV logging mirrors the reference worker's
runtime bookkeeping.
"""
from __future__ import annotations

import csv
import os
import pickle
import socket
import time
import uuid

import numpy as np

from .protocol import request


def run_worker(host: str, port: int, *, worker_id: str | None = None,
               poll_s: float = 0.3, max_generations: float = float("inf"),
               runtime_s: float = float("inf"),
               log_file: str | None = None,
               _stop_check=None) -> int:
    """Serve generations until the broker goes away / runtime ends.

    Returns the number of evaluations performed. Reconnects with backoff
    while the broker is unreachable (a worker may be started BEFORE the
    manager — reference semantics).
    """
    addr = (host, int(port))
    wid = worker_id or f"{socket.gethostname()}-{os.getpid()}-{uuid.uuid4().hex[:6]}"
    # worker-unique numpy seed: host simulate_one draws via np.random
    np.random.seed((os.getpid() * 1000003 + int(time.time())) % (2**31 - 1))
    t_end = time.time() + runtime_s if np.isfinite(runtime_s) else None
    n_eval_total = 0
    gens_served = 0
    last_counted_gen = -1
    log_writer = None
    if log_file:
        fh = open(log_file, "a", newline="")
        log_writer = csv.writer(fh)
        if fh.tell() == 0:
            log_writer.writerow(
                ["worker_id", "generation", "t", "n_eval", "n_accepted",
                 "wall_s"])

    while True:
        if _stop_check is not None and _stop_check():
            break
        if t_end and time.time() > t_end:
            break
        if gens_served >= max_generations:
            break
        try:
            reply = request(addr, ("hello", wid))
        except (ConnectionError, OSError):
            time.sleep(min(poll_s * 4, 2.0))
            continue
        if reply[0] != "work":
            time.sleep(poll_s)
            continue
        # NOTE: no served-generation memory on purpose — a transport blip
        # mid-generation must NOT bench the worker for the rest of that
        # generation; re-entering a still-running generation just pulls
        # more slots (a finished generation answers hello with "wait")
        _, gen, t, payload, batch = reply
        simulate_one = pickle.loads(payload)
        t0 = time.time()
        n_eval = n_acc = 0
        while True:
            try:
                r = request(addr, ("get_slots", wid, gen, batch))
            except (ConnectionError, OSError):
                break  # broker gone; outer loop will reconnect
            if r[0] != "slots":
                break
            _, start, stop = r
            triples = []
            for slot in range(start, stop):
                particle = simulate_one()
                n_eval += 1
                n_acc += int(bool(particle.accepted))
                triples.append((
                    slot,
                    pickle.dumps(particle, pickle.HIGHEST_PROTOCOL),
                    bool(particle.accepted),
                ))
            try:
                r2 = request(addr, ("results", wid, gen, triples))
            except (ConnectionError, OSError):
                break
            if r2[0] != "ok":
                break
        if gen != last_counted_gen:
            gens_served += 1
            last_counted_gen = gen
        n_eval_total += n_eval
        if n_eval == 0:
            # nothing handed out (generation ending / transport blip):
            # don't hot-spin on hello
            time.sleep(poll_s)
        if log_writer is not None:
            log_writer.writerow(
                [wid, gen, t, n_eval, n_acc, round(time.time() - t0, 3)])
            fh.flush()
    return n_eval_total
