"""The elastic worker: join anytime, pull slots, push particles, die freely.

Reference parity: ``pyabc/sampler/redis_eps/work.py::work`` + the
``abc-redis-worker`` CLI: a worker process polls the broker for the current
generation, evaluates ``simulate_one`` per handed-out slot, ships results
back in batches, and loops into the next generation. Death at ANY point is
safe (abandoned slots are provenance ids only); joining mid-generation is
the normal case. Per-worker CSV logging mirrors the reference worker's
runtime bookkeeping. SIGTERM/SIGINT are handled like the reference's
``KillHandler``: the current batch is finished and shipped, the worker
deregisters from the broker ("bye"), and the loop exits cleanly (exit 0) —
a cluster preemption never strands half-evaluated work.

Distributed tracing (round 8): the worker records its own phase spans —
connect / wait-for-work / deserialize / simulate-batch / serialize /
ship — on its injected monotonic clock via :class:`WorkerSpanRecorder`,
and PIGGYBACKS per-batch timing summaries on the existing result
messages (no extra round trips). Because the worker's monotonic clock
shares no epoch with the orchestrator's, every stamped request/response
exchange doubles as an NTP-style clock-offset sample
(:class:`~pyabc_tpu.observability.clock.ClockOffsetEstimator`); the
worker ships its current offset estimate + RTT-derived uncertainty with
each summary so the broker can merge the spans onto the orchestrator
timeline. ``trace=False`` reproduces the pre-round-8 wire behavior
exactly (degraded mode / protocol back-compat tests).
"""
from __future__ import annotations

import csv
import os
import pickle
import signal
import socket
import threading
import time
import uuid

import numpy as np

from ..observability import SYSTEM_CLOCK
from ..observability.clock import ClockOffsetEstimator
from ..resilience.faults import (
    FaultPlan,
    InjectedKill,
    install_fault_plan,
    maybe_fault,
)
from ..resilience.retry import RetryPolicy
from .protocol import request


class WorkerSpanRecorder:
    """Worker-side span recorder on one injected clock.

    Phase spans accumulate in a bounded pending buffer (worker-clock
    ``{"name", "start", "end", "attrs"}`` dicts) until
    :meth:`trace_payload` drains them into the next result message.
    Spans record via explicit ``begin``/``end`` tokens rather than a
    contextmanager because the simulate phase is REOPENED around
    mid-batch network ops (static-mode flushes, liveness heartbeats) —
    those round trips must not masquerade as compute time.
    """

    enabled = True

    def __init__(self, worker_id: str, clock=None, max_pending: int = 2048):
        self.worker_id = str(worker_id)
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self.offset = ClockOffsetEstimator()
        self._max_pending = int(max_pending)
        self._pending: list[dict] = []
        self.n_dropped = 0
        #: repr of the last simulate_one exception a --catch loop turned
        #: into an error record (surfaces in BrokerStatus.last_error)
        self.last_error: str | None = None
        self.n_eval = 0
        self.n_acc = 0
        #: broker round trips this worker retried (RetryPolicy backoff);
        #: ships with the trace summary -> BrokerStatus retry counts
        self.n_retries = 0

    def note_retry(self, _i=None, _exc=None) -> None:
        self.n_retries += 1

    def begin(self, name: str) -> tuple[str, float]:
        return (name, self.clock.now())

    def end(self, token: tuple[str, float] | None, **attrs) -> None:
        if token is None:
            return
        name, start = token
        end = self.clock.now()
        if end <= start:
            return
        if len(self._pending) >= self._max_pending:
            # drop oldest: recent batches matter most for live dashboards
            del self._pending[0]
            self.n_dropped += 1
        self._pending.append(
            {"name": name, "start": float(start), "end": float(end),
             "attrs": attrs}
        )

    def observe_exchange(self, t1: float, t2_broker, t4: float) -> None:
        """Feed one stamped request/response round trip into the offset
        estimator (t1/t4 worker clock, t2 the broker's stamp)."""
        if t2_broker is None:
            return
        self.offset.add_sample(t1, float(t2_broker), t4)

    def trace_payload(self, limit: int = 512) -> dict:
        """Drain pending spans into the piggyback summary dict."""
        spans = self._pending[:limit]
        del self._pending[: len(spans)]
        return {
            "v": 1,
            "spans": spans,
            "offset": self.offset.offset,
            "offset_unc": self.offset.uncertainty_s,
            "rtt": self.offset.rtt_s,
            "n_offset_samples": self.offset.n_samples,
            "n_eval": self.n_eval,
            "n_acc": self.n_acc,
            "n_dropped": self.n_dropped,
            "n_retries": self.n_retries,
            "last_error": self.last_error,
        }


class _NullRecorder:
    """Inert recorder: ``trace=False`` workers speak the pre-tracing
    protocol byte-for-byte (and pay zero bookkeeping)."""

    enabled = False
    last_error = None
    n_eval = 0
    n_acc = 0
    n_retries = 0

    def note_retry(self, _i=None, _exc=None):
        pass

    def begin(self, name):
        return None

    def end(self, token, **attrs):
        pass

    def observe_exchange(self, t1, t2, t4):
        pass

    def trace_payload(self, limit=512):
        return {}


def _broker_stamp(reply):
    """The broker-clock timestamp a trace-aware reply carries (trailing
    float), or None for pre-tracing reply shapes — every positional
    element of the legacy shapes is an int/str/bytes, so a float tail is
    unambiguous."""
    if isinstance(reply, tuple) and reply and isinstance(reply[-1], float):
        return reply[-1]
    return None


def _traced_request(addr, msg, rec, span_name: str | None = None,
                    append_t1: bool = False):
    """One broker round trip + one clock-offset sample (+ an optional
    round-trip span). ``append_t1`` marks the request trace-capable by
    appending the worker-clock send time — the broker then stamps its
    reply."""
    if not rec.enabled:
        return request(addr, msg)
    t1 = rec.clock.now()
    if append_t1:
        msg = msg + (t1,)
    token = (span_name, t1) if span_name else None
    try:
        reply = request(addr, msg, on_retry=rec.note_retry)
    except Exception:
        rec.end(token, kind=msg[0], error=True)
        raise
    t4 = rec.clock.now()
    rec.observe_exchange(t1, _broker_stamp(reply), t4)
    rec.end(token, kind=msg[0])
    return reply


def run_worker(host: str, port: int, *, worker_id: str | None = None,
               poll_s: float = 0.3, max_generations: float = float("inf"),
               runtime_s: float = float("inf"),
               log_file: str | None = None,
               catch_exceptions: bool = True,
               seed: int | None = None,
               trace: bool = True,
               clock=None,
               reconnect_base_s: float = 0.2,
               reconnect_max_s: float = 2.0,
               fault_plan: "FaultPlan | str | None" = None,
               _stop_check=None) -> int:
    """Serve generations until the broker goes away / runtime ends.

    Returns the number of evaluations performed. Reconnects with backoff
    while the broker is unreachable (a worker may be started BEFORE the
    manager — reference semantics).

    ``catch_exceptions`` (reference ``abc-redis-worker --catch``): a
    raising ``simulate_one`` ships a rejected error-record particle and
    the loop continues — a deterministic model bug then surfaces in the
    orchestrator's error records instead of serially killing every
    worker in the pool. Disable to make model errors fatal (debugging).

    ``trace``: record worker-side phase spans and piggyback them (plus
    NTP-style clock-offset samples) on result messages; ``False`` speaks
    the pre-tracing protocol exactly. ``clock``: injected monotonic
    clock (tests drive skewed VirtualClocks); defaults to the shared
    SYSTEM_CLOCK.

    ``reconnect_base_s`` / ``reconnect_max_s`` (round 9, ``abc-worker
    --reconnect-*``): capped exponential backoff while the broker is
    unreachable — the worker reconnects through broker restarts instead
    of dying on a blip (per-round-trip blips are already healed inside
    ``protocol.request`` by the shared RetryPolicy; this loop is the
    broker-DOWN layer on top, and it resets on any successful contact).
    ``fault_plan``: a :class:`~pyabc_tpu.resilience.faults.FaultPlan`
    (or parseable spec string) installed process-wide before serving —
    the ``worker.batch`` site then kills/hangs/slows THIS worker
    mid-batch deterministically. An injected KILL is a hard death:
    in-flight work is abandoned, no bye is sent, and the broker must
    discover the loss through lease expiry (exactly the production
    failure being rehearsed).
    """
    addr = (host, int(port))
    if fault_plan is not None:
        if isinstance(fault_plan, str):
            fault_plan = FaultPlan.parse(fault_plan)
        install_fault_plan(fault_plan)
    reconnect = RetryPolicy(attempts=1 << 30,
                            base_s=float(reconnect_base_s),
                            max_s=float(reconnect_max_s))
    conn_fails = 0
    wid = worker_id or f"{socket.gethostname()}-{os.getpid()}-{uuid.uuid4().hex[:6]}"
    # worker-unique numpy seed: host simulate_one draws via np.random.
    # ``seed`` (also via PYABC_TPU_WORKER_SEED) pins it for reproducible
    # tests; the default mixes the pid with os.urandom entropy — stronger
    # than the old pid+wallclock mix (two workers forked in the same
    # second shared most seed bits) and free of wall-clock reads
    env_seed = os.environ.get("PYABC_TPU_WORKER_SEED")
    if seed is None and env_seed is not None:
        seed = int(env_seed)
    if seed is None:
        seed = (os.getpid() * 1000003
                + int.from_bytes(os.urandom(4), "little"))
    np.random.seed(seed % (2**31 - 1))
    clock = clock if clock is not None else SYSTEM_CLOCK
    rec = WorkerSpanRecorder(wid, clock) if trace else _NullRecorder()
    t_end = clock.now() + runtime_s if np.isfinite(runtime_s) else None
    n_eval_total = 0
    gens_served = 0
    last_counted_gen = -1
    bye_reason = "exit"
    log_writer = None
    if log_file:
        fh = open(log_file, "a", newline="")
        log_writer = csv.writer(fh)
        if fh.tell() == 0:
            log_writer.writerow(
                ["worker_id", "generation", "t", "n_eval", "n_accepted",
                 "wall_s"])

    # reference KillHandler: SIGTERM/SIGINT request a GRACEFUL exit — the
    # in-progress batch still ships, then the worker deregisters. Handlers
    # can only be installed from the main thread (tests run workers in
    # threads; there the _stop_check hook covers shutdown instead).
    signaled = threading.Event()
    restore: dict = {}
    if threading.current_thread() is threading.main_thread():
        def _on_signal(signum, frame):
            signaled.set()

        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                restore[sig] = signal.signal(sig, _on_signal)
            except (ValueError, OSError):  # pragma: no cover - env-specific
                pass

    def stopping() -> bool:
        if signaled.is_set():
            return True
        return _stop_check() if _stop_check is not None else False

    def ship(gen: int, parts: list) -> tuple:
        """Serialize + ship one batch of (slot, particle, accepted)
        triples; the serialize and ship phases get their own spans, and
        the trace summary rides the SAME message (no extra round trip)."""
        ser_tok = rec.begin("worker.serialize")
        triples = [
            (slot, pickle.dumps(p, pickle.HIGHEST_PROTOCOL), acc)
            for slot, p, acc in parts
        ]
        rec.end(ser_tok, n=len(triples),
                nbytes=sum(len(b) for _s, b, _a in triples))
        msg = ("results", wid, gen, triples)
        if rec.enabled:
            msg = msg + (rec.trace_payload(),)
        return _traced_request(addr, msg, rec, span_name="worker.ship")

    connect_tok = rec.begin("worker.connect")
    wait_tok = None
    hard_killed = False
    try:
        while True:
            if stopping():
                bye_reason = "signal"
                break
            if t_end and clock.now() > t_end:
                bye_reason = "runtime_end"
                break
            if gens_served >= max_generations:
                bye_reason = "max_generations"
                break
            if connect_tok is None and wait_tok is None:
                wait_tok = rec.begin("worker.wait")
            try:
                reply = _traced_request(addr, ("hello", wid), rec,
                                        append_t1=True)
            except (ConnectionError, OSError):
                # broker unreachable: capped exponential reconnect
                # backoff (resets on the next successful contact)
                time.sleep(reconnect.delay_s(min(conn_fails, 16)))
                conn_fails += 1
                continue
            conn_fails = 0
            if connect_tok is not None:
                # first successful broker contact (covers pre-manager
                # startup backoff — reference "worker before manager")
                rec.end(connect_tok)
                connect_tok = None
            if reply[0] != "work":
                time.sleep(poll_s)
                continue
            if wait_tok is not None:
                rec.end(wait_tok)
                wait_tok = None
            # NOTE: no served-generation memory on purpose — a transport
            # blip mid-generation must NOT bench the worker for the rest of
            # that generation; re-entering a still-running generation just
            # pulls more slots (a finished generation answers hello "wait")
            gen, t, payload, batch, mode = reply[1:6]
            de_tok = rec.begin("worker.deserialize")
            simulate_one = pickle.loads(payload)
            rec.end(de_tok, nbytes=len(payload), gen=gen)
            t0 = clock.now()
            n_eval = n_acc = 0
            while True:
                try:
                    r = _traced_request(
                        addr, ("get_slots", wid, gen, batch), rec,
                        span_name="worker.slots", append_t1=True,
                    )
                except (ConnectionError, OSError):
                    break  # broker gone; outer loop will reconnect
                if r[0] != "slots":
                    break
                start, stop = r[1], r[2]
                # fault-plan site: the handed-out slots are LEASED to this
                # worker now — an injected kill here abandons them
                # mid-batch, exactly the self-healing scenario the
                # broker's lease requeue must absorb
                maybe_fault("worker.batch", worker_id=wid, gen=gen,
                            start=start, stop=stop)
                parts = []  # (slot, particle, accepted) — serialized at ship
                aborted = False
                sim_tok = rec.begin("worker.simulate")
                for slot in range(start, stop):
                    # dynamic: one evaluation per slot. static: a quota
                    # unit — evaluate until THIS unit accepts (reference
                    # RedisStaticSampler / MappingSampler semantics);
                    # rejects ship as records either way.
                    unit_evals = 0
                    while True:
                        try:
                            particle = simulate_one()
                        except Exception as e:
                            if not catch_exceptions:
                                raise
                            from ..core.population import Particle

                            rec.last_error = repr(e)[:300]
                            particle = Particle(
                                m=-1, parameter={}, weight=0.0,
                                sum_stat={}, distance=float("inf"),
                                accepted=False, error=repr(e),
                            )
                        n_eval += 1
                        unit_evals += 1
                        rec.n_eval += 1
                        accepted = bool(particle.accepted)
                        parts.append((slot, particle, accepted))
                        if accepted:
                            n_acc += 1
                            rec.n_acc += 1
                        if accepted or mode != "static":
                            break
                        if stopping():
                            # preemption mid-unit: ship what we have and
                            # exit — delay bounded by ONE simulate_one
                            aborted = True
                            break
                        if mode == "static" and len(parts) >= 64:
                            # a spinning static unit (collapsed acceptance
                            # or a deterministically-raising model under
                            # --catch) must not hoard its reject/error
                            # records unboundedly: flush them mid-unit so
                            # errors surface and memory stays bounded
                            rec.end(sim_tok, n_eval=len(parts))
                            try:
                                rf = ship(gen, parts)
                            except (ConnectionError, OSError):
                                aborted = True
                                parts = []
                                sim_tok = None
                                break
                            parts = []
                            sim_tok = rec.begin("worker.simulate")
                            if rf[0] != "ok":
                                aborted = True
                                break
                        if unit_evals % 256 == 0:
                            # liveness probe: a static unit can spin for
                            # thousands of evaluations at a collapsed
                            # acceptance rate; abandon it as soon as the
                            # broker finalized the generation (eval
                            # budget / another worker finished it)
                            rec.end(sim_tok, n_eval=len(parts))
                            sim_tok = None
                            try:
                                hb = _traced_request(
                                    addr, ("heartbeat", wid, gen), rec,
                                    span_name="worker.slots",
                                    append_t1=True,
                                )
                            except (ConnectionError, OSError):
                                aborted = True
                                break
                            if hb[0] != "ok":
                                aborted = True
                                break
                            sim_tok = rec.begin("worker.simulate")
                    if aborted or stopping():
                        aborted = True
                        break
                rec.end(sim_tok, n_eval=len(parts))
                try:
                    r2 = ship(gen, parts)
                except (ConnectionError, OSError):
                    break
                if r2[0] != "ok" or aborted or stopping():
                    # graceful shutdown: the batch above was flushed; stop
                    # pulling new slots
                    break
            if gen != last_counted_gen:
                gens_served += 1
                last_counted_gen = gen
            n_eval_total += n_eval
            if n_eval == 0 and not stopping():
                # nothing handed out (generation ending / transport blip):
                # don't hot-spin on hello
                time.sleep(poll_s)
            if log_writer is not None:
                log_writer.writerow(
                    [wid, gen, t, n_eval, n_acc,
                     round(clock.now() - t0, 3)])
                fh.flush()
    except InjectedKill:
        # simulated SIGKILL (fault plan): die HARD — no batch flush, no
        # bye, no final trace. The broker's lease table must discover
        # the abandoned slots and requeue them to a live worker.
        hard_killed = True
    finally:
        if stopping():
            bye_reason = "signal"
        # deregister so manager status doesn't show ghost workers; the
        # final trace flushes ship spans the last results reply couldn't
        # carry (their end time postdates that message)
        try:
            if hard_killed:
                pass
            elif rec.enabled:
                request(addr, ("bye", wid, bye_reason, rec.trace_payload()))
            else:
                request(addr, ("bye", wid))
        except (ConnectionError, OSError):
            pass
        for sig, old in restore.items():
            try:
                signal.signal(sig, old)
            except (ValueError, OSError):  # pragma: no cover
                pass
    return n_eval_total
