"""The evaluation broker: atomic slot handout + result collection.

Reference parity: ``pyabc/sampler/redis_eps/sampler.py``'s Redis-side
bookkeeping — ``N_EVAL``/``N_ACC``/``N_WORKER`` counters, the result
queue, and the generation start/stop signaling — implemented as one
threaded TCP server with an in-process lock instead of a Redis instance.

Elasticity contract (the reference's signature capability, SURVEY.md
§5.3): workers are never registered ahead of time and never waited upon.
A worker that dies mid-generation simply stops requesting slots — its
outstanding slot ids are abandoned, which is harmless: slots are
provenance ids for the deterministic sort-by-slot trim, and generation
completion is driven solely by DELIVERED accepted results. A worker that
joins mid-generation gets the current generation's payload on hello.

Self-healing (round 9): every slot handout is a LEASE — ``(worker,
range, deadline)`` on the injected clock (``resilience/lease.py``). A
lease whose owner goes silent past the liveness window, or undelivered
past ``lease_timeout_s``, requeues its undelivered slots; the next
``get_slots`` from a live worker redispatches them (counted in
``pyabc_tpu_batches_redispatched_total``, with the orphaned window
recorded as a ``recovery.redispatch`` span for gap attribution).
Slot-level dedup drops a late duplicate delivery exactly-once, so the
original owner limping back cannot double-count a batch. This closes
the two stalls the pre-round-9 contract conceded: ``wait_for_all``
(waits for every handed-out slot's delivery) and ``mode="static"``
(fixed acceptance quotas) previously stalled until the sampler's
``generation_timeout`` when a worker crashed with work in flight —
now the work is re-handed to the survivors and the generation
completes.

Distributed tracing (round 8): trace-capable workers append a worker-clock
send time to their requests; the broker answers those with its own
monotonic clock appended, turning every exchange into a clock-offset
sample the WORKER evaluates (protocol.py documents the shapes). Result
messages piggyback per-batch phase-span summaries; the broker ingests
them into a bounded per-process buffer, offset-maps each span onto ITS
clock (the orchestrator timeline — broker and sampler share one process
clock), and :meth:`EvalBroker.drain_worker_spans` hands them to the
sampler's tracer as per-worker pseudo-threads. Per-worker clock offsets,
RTT uncertainty, last errors and departure reasons live in the worker
table and surface through :meth:`status` / :meth:`worker_snapshot`.
"""
from __future__ import annotations

import socketserver
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field

from ..observability import SYSTEM_CLOCK, dispatch_sources_snapshot, \
    global_metrics, register_worker_source
from ..observability.metrics import (
    BATCHES_REDISPATCHED_TOTAL,
    DUPLICATES_DROPPED_TOTAL,
    LEASES_EXPIRED_TOTAL,
)
from ..resilience.lease import LeaseTable
from .protocol import recv_msg, send_msg

#: a worker not heard from for this long while a generation is OPEN is
#: flagged presumed_dead in status() — the "wait() stalls dark when a
#: worker dies mid-batch" diagnosis, as data instead of a mystery
DEFAULT_LIVENESS_S = 5.0

#: slot batches are LEASES (round 9): a handed-out range not delivered
#: within this window — and not refreshed by ANY contact from its owner
#: (any message extends the owner's leases) — requeues to live workers.
#: The presumed-dead rule usually fires first (liveness_s = 5 s); this
#: is the backstop for a worker that keeps polling but never delivers.
DEFAULT_LEASE_TIMEOUT_S = 15.0

#: bound on the ingested worker-span buffer (drained every generation by
#: the sampler; the bound only matters for broker use without one)
MAX_WORKER_SPANS = 100_000

#: bound on the recovery-action log surfaced via status()/abc-manager
MAX_RECOVERY_LOG = 200


@dataclass
class BrokerStatus:
    generation: int
    t: int | None
    n_target: int
    n_acc: int
    n_eval_handed: int
    n_results: int
    workers: dict = field(default_factory=dict)
    done: bool = True
    #: workers that deregistered ("bye"), keyed by worker id:
    #: {"reason", "last_seen", "n_results"} — a terminated worker leaves
    #: a tombstone instead of vanishing from the books
    departed: dict = field(default_factory=dict)
    #: lease bookkeeping (round 9): outstanding/requeued slot counts plus
    #: the run-lifetime redispatch / dedup / expiry counters
    leases: dict = field(default_factory=dict)
    #: tail of the recovery-action log ({"action", "wid", "ts", ...}) —
    #: what the self-healing machinery DID, surfaced in abc-manager
    recovery: list = field(default_factory=list)
    #: broker round trips the workers reported retrying (summed from the
    #: per-worker trace summaries; per-worker counts in ``workers``)
    n_request_retries: int = 0
    #: dispatch-engine state of fused runs live in THIS process (round
    #: 12): in-flight chunks, speculative rollbacks, sync budget —
    #: surfaced in ``abc-manager`` so a mixed elastic+fused orchestrator
    #: shows both halves of its dispatch health in one place
    dispatch: list = field(default_factory=list)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):  # one request per connection (stateless workers)
        broker: EvalBroker = self.server.broker  # type: ignore[attr-defined]
        try:
            msg = recv_msg(self.request)
        except (ConnectionError, ValueError, EOFError):
            return
        try:
            reply = broker._dispatch(msg)
        except Exception as e:  # defensive: a bad frame must not kill serve
            reply = ("error", repr(e))
        try:
            send_msg(self.request, reply)
        except (ConnectionError, BrokenPipeError, OSError):
            pass


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class EvalBroker:
    """Owns generation state; thread-safe; runs inside the sampler process.

    SECURITY: frames are pickle — anyone who can reach the port can run
    code in this process (same trust model as the reference's Redis
    instance, which is equally unauthenticated by default). The default
    bind is loopback; bind ``0.0.0.0`` explicitly ONLY on a trusted
    cluster network.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_eval: float = float("inf"), clock=None,
                 liveness_s: float = DEFAULT_LIVENESS_S,
                 lease_timeout_s: float = DEFAULT_LEASE_TIMEOUT_S,
                 metrics=None):
        # injected monotonic clock (observability subsystem): worker
        # liveness ages and wait deadlines survive wall-clock steps, and
        # tests can drive a VirtualClock
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self.liveness_s = float(liveness_s)
        # recovery counters always collect (global registry default):
        # a dashboard scraping an unconfigured process still sees them
        self.metrics = metrics if metrics is not None else global_metrics()
        #: self-healing lease table (round 9): every slot handout is a
        #: lease; expired / presumed-dead work requeues to live workers
        #: with slot-level dedup for the late duplicates
        self._leases = LeaseTable(self.clock, timeout_s=lease_timeout_s)  # abc-lint: guarded-by=_lock
        #: recovery actions taken (requeue/redispatch), newest last
        self._recovery_log: list[dict] = []  # abc-lint: guarded-by=_lock
        #: recovery spans ready for the sampler's tracer (same drain
        #: pattern as the worker spans): orphaned->redispatched windows
        self._recovery_spans: list[dict] = []  # abc-lint: guarded-by=_lock
        self._lock = threading.Lock()
        self._gen = 0               # monotonically increasing generation id
        self._payload: bytes | None = None  # pickled simulate_one closure
        self._t: int | None = None
        self._n_target = 0
        self._max_eval = max_eval
        self._all_accepted = False
        self._next_slot = 0
        self._n_acc = 0
        self._n_delivered = 0
        self._batch = 1
        self._wait_for_all = False
        self._mode = "dynamic"
        self._draining = False
        self._collect_only = False
        self._results: list[tuple[int, bytes, bool]] = []  # abc-lint: guarded-by=_lock
        self._done = True
        self._done_event = threading.Event()
        #: look-ahead: a pre-published NEXT generation, auto-started the
        #: moment the current one finalizes (reference redis look-ahead:
        #: SSA(t+1) is on the broker BEFORE t ends, so workers roll into
        #: t+1 with zero idle while the orchestrator persists/adapts)
        self._pending_next: tuple | None = None  # abc-lint: guarded-by=_lock
        #: finished results keyed by generation id, bounded to the last
        #: few generations — ``wait()``/``last_results()`` must survive a
        #: pre-published generation auto-starting AND finalizing between
        #: two 50 ms polls (a single-entry buffer silently dropped the
        #: awaited generation in that race)
        self._finished: "OrderedDict[int, list]" = OrderedDict()  # abc-lint: guarded-by=_lock
        # the auto-advance race needs at most 2 (awaited gen + one
        # pending_next that started AND finished between polls); 3 adds
        # margin without pinning generations of pickled particles
        self._finished_keep = 3
        #: broker-clock finalization instant per finished generation —
        #: the sampler subtracts it from its own observation time to
        #: measure the ORCHESTRATOR POLL LATENCY slice of dark time
        self._finished_at: "OrderedDict[int, float]" = OrderedDict()  # abc-lint: guarded-by=_lock
        self._workers: dict[str, dict] = {}  # abc-lint: guarded-by=_lock
        #: bye tombstones: {wid: {"reason", "last_seen", "n_results"}}
        self._departed: dict[str, dict] = {}  # abc-lint: guarded-by=_lock
        #: ingested worker spans, already offset-mapped onto THIS clock
        self._worker_spans: list[dict] = []  # abc-lint: guarded-by=_lock
        self._worker_spans_dropped = 0
        self._server = _Server((host, port), _Handler)
        self._server.broker = self  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="pyabc-tpu-broker",
        )
        self._thread.start()
        # the dashboard's /api/observability worker section (weakref —
        # a dropped broker silently leaves the snapshot)
        register_worker_source(self)

    # ------------------------------------------------------------------ api
    @property
    def address(self) -> tuple[str, int]:
        host, port = self._server.server_address[:2]
        return (host, port)

    def start_generation(self, t: int, payload: bytes, n_target: int,
                         *, max_eval: float = float("inf"),
                         all_accepted: bool = False,
                         batch: int = 1,
                         wait_for_all: bool = False,
                         mode: str = "dynamic") -> None:
        """``mode``: 'dynamic' hands out evaluation slots until n_target
        acceptances arrive (reference RedisEvalParallelSampler); 'static'
        hands out exactly n_target ACCEPTANCE quota units, each evaluated
        until it accepts (reference RedisStaticSampler / MappingSampler
        semantics — a worker dying with undelivered units stalls the
        generation until the sampler's timeout, the static scheduler's
        inherent weakness). ``wait_for_all``: after the acceptance target
        is met, stop handing out new slots but finish only once every
        handed-out slot's result has been DELIVERED, so adaptive
        components see an unbiased, complete record set (reference
        ``wait_for_all_samples``)."""
        if mode not in ("dynamic", "static"):
            raise ValueError(f"unknown scheduling mode {mode!r}")
        with self._lock:
            self._advance_locked(t, payload, int(n_target), max_eval,
                                 bool(all_accepted), max(int(batch), 1),
                                 bool(wait_for_all), mode, False)

    def _advance_locked(self, t, payload, n_target, max_eval, all_accepted,
                        batch, wait_for_all, mode, collect_only) -> None:
        self._gen += 1
        self._t = t
        self._payload = payload
        self._n_target = n_target
        self._max_eval = max_eval
        self._all_accepted = all_accepted
        self._batch = batch
        self._wait_for_all = wait_for_all
        self._mode = mode
        self._collect_only = collect_only
        self._next_slot = 0
        self._n_acc = 0
        self._n_delivered = 0
        self._draining = False
        self._results = []
        self._done = False
        self._done_event.clear()
        self._leases.reset()

    def pre_publish(self, t: int, payload: bytes, n_target: int, *,
                    batch: int = 1,
                    max_eval: float = float("inf")) -> None:
        """Queue the NEXT generation's closure; it auto-starts in
        COLLECT-ONLY mode the instant the current generation finalizes
        (look-ahead: completion is then driven by the sampler's host-side
        delayed acceptance via results_snapshot/finish_generation, since
        workers cannot test acceptance against a not-yet-known epsilon)."""
        with self._lock:
            self._pending_next = (
                t, payload, int(n_target), max_eval, max(int(batch), 1),
            )
            if self._done:
                t_, p_, n_, me_, b_ = self._pending_next
                self._pending_next = None
                self._advance_locked(t_, p_, n_, me_, False, b_, False,
                                     "dynamic", True)

    def cancel_pre_published(self) -> None:
        """Drop a queued (not yet started) look-ahead generation."""
        with self._lock:
            self._pending_next = None

    def results_snapshot(self) -> tuple[list[tuple[int, bytes, bool]],
                                        bool, int]:
        """(results-so-far, done, generation id) without blocking."""
        with self._lock:
            return list(self._results), self._done, self._gen

    def finish_generation(self) -> None:
        """Finalize the active generation (look-ahead delayed acceptance:
        the SAMPLER decides completion from unpickled distances)."""
        with self._lock:
            if not self._done:
                self._finish_locked()

    def last_results(self, gen: int):
        """The finished results of generation ``gen``, or None if that
        generation never finished or was evicted (the finished buffer
        retains the last few generations)."""
        with self._lock:
            res = self._finished.get(gen)
            return list(res) if res is not None else None

    def finished_at(self, gen: int) -> float | None:
        """Broker-clock instant generation ``gen`` finalized (None if it
        never finished or was evicted) — the sampler's poll-latency
        anchor."""
        with self._lock:
            return self._finished_at.get(gen)

    def wait(self, poll_s: float = 0.05, timeout: float | None = None
             ) -> list[tuple[int, bytes, bool]]:
        """Block until the generation completes; returns (slot,
        particle_bytes, accepted) triples of every delivered result.
        Generation-stamped: if a pre-published look-ahead generation
        auto-started meanwhile, the FINISHED generation's results are
        returned from the last-finished buffer."""
        deadline = self.clock.now() + timeout if timeout else None
        with self._lock:
            gen0 = self._gen
            if self._done and gen0 in self._finished:
                return list(self._finished[gen0])
        while True:
            with self._lock:
                if self._gen != gen0:
                    res = self._finished.get(gen0)
                    if res is None:
                        raise RuntimeError(
                            f"generation {gen0} was superseded without its "
                            f"results being available: it either never "
                            f"finalized (start_generation replaced it) or "
                            f"was evicted from the finished buffer (keeps "
                            f"{self._finished_keep} generations)"
                        )
                    return list(res)
                if self._done:
                    return list(self._results)
            time.sleep(poll_s)
            if deadline and self.clock.now() > deadline:
                raise TimeoutError(
                    f"generation incomplete: {self.status()}"
                )

    def status(self) -> BrokerStatus:
        with self._lock:
            # status observation doubles as a reap point: even with no
            # worker polling get_slots, abc-manager / the sampler's
            # metrics poll keeps the lease table honest
            if not self._done:
                self._reap_leases_locked()
            now = self.clock.now()
            gen_open = not self._done
            workers = {}
            for w, info in self._workers.items():
                idle = now - info["last_seen"]
                view = dict(info)
                view["idle_s"] = round(idle, 1)
                # the wait()-stalls-dark diagnosis: a silent worker while
                # the generation is open is flagged, with its last error
                # (if its --catch loop reported one) right next to it
                view["presumed_dead"] = bool(
                    gen_open and idle > self.liveness_s
                )
                workers[w] = view
            return BrokerStatus(
                generation=self._gen, t=self._t, n_target=self._n_target,
                n_acc=self._n_acc, n_eval_handed=self._next_slot,
                n_results=len(self._results),
                workers=workers,
                done=self._done,
                departed=dict(self._departed),
                leases=self._leases.stats(),
                recovery=list(self._recovery_log[-20:]),
                n_request_retries=sum(
                    int(info.get("n_retries", 0) or 0)
                    for info in self._workers.values()
                ),
                dispatch=dispatch_sources_snapshot(),
            )

    def worker_snapshot(self) -> dict:
        """JSON-ready per-worker view for ``observability_snapshot()`` /
        the dashboard's ``/api/observability``: liveness, clock offset +
        uncertainty, throughput counters, last error, departures."""
        st = self.status()
        out = {}
        for w, info in st.workers.items():
            out[w] = {
                "last_seen_idle_s": info.get("idle_s"),
                "presumed_dead": info.get("presumed_dead", False),
                "n_results": info.get("n_results", 0),
                "n_eval": info.get("n_eval", 0),
                "n_acc": info.get("n_acc", 0),
                "n_retries": info.get("n_retries", 0),
                "clock_offset_s": info.get("clock_offset_s"),
                "clock_offset_unc_s": info.get("clock_offset_unc_s"),
                "clock_rtt_s": info.get("clock_rtt_s"),
                "last_error": info.get("last_error"),
                "last_recovery": info.get("last_recovery"),
                "trace": bool(info.get("trace", False)),
            }
        out["__leases__"] = st.leases
        out["__recovery__"] = st.recovery
        for w, info in st.departed.items():
            out.setdefault(w, {})["departed"] = info
        return out

    def worker_offsets(self) -> dict:
        """{worker_id: {"offset_s", "uncertainty_s", "rtt_s"}} for every
        trace-reporting worker (tests + the bench's merge-uncertainty
        guard)."""
        with self._lock:
            return {
                w: {
                    "offset_s": info.get("clock_offset_s"),
                    "uncertainty_s": info.get("clock_offset_unc_s"),
                    "rtt_s": info.get("clock_rtt_s"),
                }
                for w, info in self._workers.items()
                if info.get("clock_offset_s") is not None
            }

    def drain_worker_spans(self) -> list[dict]:
        """Take (and clear) the ingested worker spans: ``Span.to_dict``-
        shaped dicts already offset-mapped onto this broker's clock, on
        per-worker pseudo-threads (``worker:<id>``), each carrying the
        offset estimate + RTT uncertainty it was mapped with. The sampler
        records them onto the run tracer after every generation."""
        with self._lock:
            spans, self._worker_spans = self._worker_spans, []
            return spans

    def drain_recovery_spans(self) -> list[dict]:
        """Take (and clear) the recovery spans (``recovery.redispatch``:
        the orphaned->redispatched window of each healed batch, on this
        broker's clock, ``recovery`` pseudo-thread). The sampler records
        them on the run tracer so ``elastic_gap_attribution`` reports the
        recovery-time slice of dark time."""
        with self._lock:
            spans, self._recovery_spans = self._recovery_spans, []
            return spans

    def stop(self) -> None:
        with self._lock:
            self._done = True
        self._done_event.set()
        self._server.shutdown()
        self._server.server_close()
        from ..observability import unregister_worker_source

        unregister_worker_source(self)

    # ------------------------------------------------------------ dispatch
    def _touch_locked(self, worker_id: str, **updates) -> None:
        info = self._workers.setdefault(
            worker_id, {"n_results": 0, "joined": self.clock.now()}
        )
        info["last_seen"] = self.clock.now()
        for k, v in updates.items():
            info[k] = info.get(k, 0) + v
        # any contact proves life: a slow-but-alive worker keeps its
        # leased batches (the lease timeout targets SILENT owners)
        self._leases.touch_worker(worker_id)

    def _reap_leases_locked(self) -> None:
        """Requeue leases whose owners are presumed dead or timed out."""
        now = self.clock.now()
        dead = [
            w for w, info in self._workers.items()
            if not self._done and now - info["last_seen"] > self.liveness_s
        ]
        events = self._leases.reap(now, dead)
        for ev in events:
            self.metrics.counter(
                LEASES_EXPIRED_TOTAL,
                "batch leases reaped (expired or owner presumed dead) "
                "and requeued",
            ).inc()
            self._log_recovery_locked({
                "action": "requeue", "wid": ev["wid"], "ts": now,
                "n_slots": ev["n_slots"], "reason": ev["reason"],
                "gen": self._gen,
            })

    def _log_recovery_locked(self, entry: dict) -> None:
        self._recovery_log.append(entry)
        del self._recovery_log[:-MAX_RECOVERY_LOG]
        # remember the last recovery action against the involved worker
        info = self._workers.get(entry.get("wid"))
        if info is not None:
            info["last_recovery"] = (
                f"{entry['action']}:{entry.get('reason', '')}"
                f"@{round(entry['ts'], 1)}"
            )

    def _serve_requeued_locked(self, worker_id: str, k: int):
        """Hand a requeued range to ``worker_id`` if any is waiting.

        Returns ``(start, stop)`` or None. Emits the redispatch counter
        and a ``recovery.redispatch`` span covering the orphaned window
        (requeued-at -> now) so gap attribution sees recovery time."""
        taken = self._leases.take_requeued(worker_id, k)
        if taken is None:
            return None
        start, stop, orphaned_at = taken
        now = self.clock.now()
        self.metrics.counter(
            BATCHES_REDISPATCHED_TOTAL,
            "requeued batches re-handed to live workers",
        ).inc()
        self._log_recovery_locked({
            "action": "redispatch", "wid": worker_id, "ts": now,
            "n_slots": stop - start, "gen": self._gen,
            "orphaned_s": round(now - orphaned_at, 6),
        })
        if now > orphaned_at:
            self._recovery_spans.append({
                "name": "recovery.redispatch", "span_id": None,
                "parent_id": None, "thread": "recovery",
                "start": float(orphaned_at), "end": float(now),
                "attrs": {"worker_id": worker_id, "gen": self._gen,
                          "n_slots": int(stop - start)},
            })
            del self._recovery_spans[:-MAX_RECOVERY_LOG]
        return (start, stop)

    def _ingest_trace_locked(self, worker_id: str, trace: dict) -> None:
        """Store a piggybacked trace summary: update the worker's offset/
        error fields and offset-map its phase spans onto this clock."""
        if not isinstance(trace, dict) or trace.get("v") != 1:
            return
        info = self._workers.setdefault(
            worker_id, {"n_results": 0, "joined": self.clock.now()}
        )
        info["trace"] = True
        offset = trace.get("offset")
        if offset is not None:
            info["clock_offset_s"] = float(offset)
            info["clock_offset_unc_s"] = trace.get("offset_unc")
            info["clock_rtt_s"] = trace.get("rtt")
        if trace.get("last_error"):
            info["last_error"] = str(trace["last_error"])[:300]
        for k in ("n_eval", "n_acc", "n_retries"):
            if isinstance(trace.get(k), int):
                info[k] = trace[k]
        if offset is None:
            # spans on an uncalibrated clock cannot be merged; count them
            self._worker_spans_dropped += len(trace.get("spans") or ())
            return
        unc = trace.get("offset_unc")
        for sp in trace.get("spans") or ():
            try:
                start = float(sp["start"]) + float(offset)
                end = float(sp["end"]) + float(offset)
            except (KeyError, TypeError, ValueError):
                continue
            attrs = dict(sp.get("attrs") or {})
            attrs.update({
                "worker_id": worker_id,
                "clock_offset_s": float(offset),
                "clock_offset_unc_s": unc,
                "worker_clock_start": sp["start"],
            })
            self._worker_spans.append({
                "name": str(sp.get("name", "worker.phase")),
                "span_id": None, "parent_id": None,
                "thread": f"worker:{worker_id}",
                "start": start, "end": end, "attrs": attrs,
            })
        if len(self._worker_spans) > MAX_WORKER_SPANS:
            drop = len(self._worker_spans) - MAX_WORKER_SPANS
            del self._worker_spans[:drop]
            self._worker_spans_dropped += drop

    def _dispatch(self, msg):
        kind = msg[0]
        # trace-capable requests carry a worker-clock send time (or a
        # trace dict for results/bye); stamping the reply with THIS clock
        # completes the NTP-style exchange the worker's offset estimator
        # consumes — same round trip, no extra messages
        t_broker = float(self.clock.now())
        if kind == "hello":
            traced = len(msg) >= 3
            with self._lock:
                self._touch_locked(msg[1])
                if self._done or self._payload is None:
                    return ("wait", t_broker) if traced else ("wait",)
                reply = ("work", self._gen, self._t, self._payload,
                         self._batch, self._mode)
                return reply + (t_broker,) if traced else reply
        if kind == "get_slots":
            worker_id, gen, k = msg[1], msg[2], msg[3]
            traced = len(msg) >= 5
            with self._lock:
                self._touch_locked(worker_id)
                if gen != self._gen or self._done:
                    return ("done", t_broker) if traced else ("done",)
                # self-healing: requeue expired / presumed-dead leases,
                # then serve orphaned work FIRST — also while draining,
                # which is exactly when an abandoned batch would
                # otherwise stall the generation until the timeout
                self._reap_leases_locked()
                requeued = self._serve_requeued_locked(worker_id, int(k))
                if requeued is not None:
                    start, stop = requeued
                    if traced:
                        return ("slots", start, stop, t_broker)
                    return ("slots", start, stop)
                if self._draining:
                    return ("done", t_broker) if traced else ("done",)
                cap = self._max_eval
                if self._mode == "static":
                    # static quota: exactly n_target acceptance units total
                    cap = min(cap, self._n_target)
                if self._next_slot >= cap:
                    if self._mode == "static":
                        # every unit handed out; completion is driven by
                        # their deliveries, not by refusing stragglers
                        return ("done", t_broker) if traced else ("done",)
                    # eval budget exhausted: finish with what was delivered
                    self._finish_locked()
                    return ("done", t_broker) if traced else ("done",)
                start = self._next_slot
                stop = int(min(start + int(k), cap))
                self._next_slot = stop
                self._leases.grant(worker_id, start, stop)
                if traced:
                    return ("slots", start, stop, t_broker)
                return ("slots", start, stop)
        if kind == "results":
            worker_id, gen, triples = msg[1], msg[2], msg[3]
            trace = msg[4] if len(msg) >= 5 else None
            traced = trace is not None

            def _reply(tag: str):
                return (tag, t_broker) if traced else (tag,)

            with self._lock:
                self._touch_locked(worker_id, n_results=len(triples))
                if traced:
                    self._ingest_trace_locked(worker_id, trace)
                if gen != self._gen:
                    return _reply("done")
                if self._done:
                    return _reply("done")
                n_dup = 0
                n_admitted = 0
                n_admitted_acc = 0
                for slot, blob, accepted in triples:
                    slot = int(slot)
                    accepted = bool(accepted)
                    # release the slot from its lease (whoever held it),
                    # then exactly-once dedup: a redispatched batch's
                    # LATE original delivery — or a RetryPolicy-resent
                    # results frame whose first reply was lost — must
                    # not double-count
                    self._leases.note_delivery(slot)
                    if not self._leases.admit(slot, accepted, self._mode):
                        n_dup += 1
                        continue
                    self._results.append((slot, blob, accepted))
                    n_admitted += 1
                    if accepted:
                        self._n_acc += 1
                        n_admitted_acc += 1
                if n_dup:
                    self.metrics.counter(
                        DUPLICATES_DROPPED_TOTAL,
                        "late duplicate deliveries dropped by slot-level "
                        "dedup",
                    ).inc(n_dup)
                    self._log_recovery_locked({
                        "action": "dedup_drop", "wid": worker_id,
                        "ts": self.clock.now(), "n_slots": n_dup,
                        "gen": self._gen,
                    })
                # dynamic slots yield exactly one triple each; static quota
                # units yield one ACCEPTED triple each (plus reject records)
                self._n_delivered += (
                    n_admitted_acc if self._mode == "static" else n_admitted
                )
                if self._collect_only:
                    # look-ahead generation: completion is the sampler's
                    # call (delayed acceptance against the final epsilon)
                    return _reply("ok")
                if self._mode == "static" \
                        and len(self._results) >= self._max_eval:
                    # static eval budget: every static evaluation ships a
                    # triple (rejects included), so the delivered count IS
                    # the evaluation count (in-progress units overshoot by
                    # at most their heartbeat interval). Finish partial —
                    # the sampler's n_accepted < n then triggers ABCSMC's
                    # acceptance-budget stop, like the dynamic slot cap.
                    self._finish_locked()
                    return _reply("done")
                # draining implies the target was already met (n_acc is
                # monotonic), so one branch decides both finalizations
                if self._n_acc >= self._n_target:
                    if not self._wait_for_all \
                            or self._n_delivered >= self._next_slot:
                        self._finish_locked()
                        return _reply("done")
                    # target met: stop handing out new slots, keep
                    # collecting the in-flight ones so adaptive
                    # components see the complete record set
                    self._draining = True
                return _reply("ok")
        if kind == "heartbeat":
            # static-unit liveness probe: lets a worker abandon a spinning
            # quota unit the moment the generation is finalized
            worker_id, gen = msg[1], msg[2]
            traced = len(msg) >= 4
            with self._lock:
                self._touch_locked(worker_id)
                if gen != self._gen or self._done or self._draining:
                    return ("done", t_broker) if traced else ("done",)
                return ("ok", t_broker) if traced else ("ok",)
        if kind == "bye":
            # graceful worker shutdown (KillHandler parity): deregister so
            # manager status doesn't show ghosts; trace-capable workers
            # attach a reason + their final span flush, leaving a
            # tombstone instead of a blank
            with self._lock:
                if len(msg) >= 4:
                    self._ingest_trace_locked(msg[1], msg[3])
                info = self._workers.pop(msg[1], None)
                self._departed[msg[1]] = {
                    "reason": msg[2] if len(msg) >= 3 else "bye",
                    "last_seen": (info or {}).get("last_seen"),
                    "n_results": (info or {}).get("n_results", 0),
                    "last_error": (info or {}).get("last_error"),
                }
            return ("ok",)
        if kind == "status":
            return ("status", self.status())
        if kind == "shutdown":
            with self._lock:
                self._finish_locked()
            return ("ok",)
        return ("error", f"unknown request {kind!r}")

    def _finish_locked(self) -> None:
        self._done = True
        self._finished[self._gen] = list(self._results)
        self._finished_at[self._gen] = self.clock.now()
        while len(self._finished) > self._finished_keep:
            self._finished.popitem(last=False)
        while len(self._finished_at) > self._finished_keep:
            self._finished_at.popitem(last=False)
        self._done_event.set()
        if self._pending_next is not None:
            # look-ahead auto-advance: workers roll straight into the
            # pre-published next generation (collect-only — the sampler
            # applies delayed acceptance host-side)
            t, payload, n_target, max_eval, batch = self._pending_next
            self._pending_next = None
            self._advance_locked(t, payload, n_target, max_eval, False,
                                 batch, False, "dynamic", True)
