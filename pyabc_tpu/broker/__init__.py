"""Elastic host-worker farming over TCP (the Redis-sampler analog).

Reference parity: ``pyabc/sampler/redis_eps/{sampler,worker,cli}.py``
(SURVEY.md §2.3 Redis row, §5.3): a broker hands out evaluation slots to
workers that may JOIN AND LEAVE AT ANY TIME — mid-generation included; a
SIGKILLed worker costs nothing but throughput. Implemented with stdlib
sockets (no redis dependency): the broker is a length-prefixed-pickle TCP
server owned by :class:`ElasticSampler`; ``abc-worker`` processes connect
from anywhere, pull slots in batches, and push back evaluated particles.

This serves HOST-side (non-traceable) models — external simulators, R /
Julia / shell models — scaled across machines. Traceable JaxModels scale
via the device mesh instead (``BatchedSampler`` + ``jax.sharding``).
"""
from .broker import BrokerStatus, EvalBroker
from .sampler import ElasticSampler
from .worker import run_worker

__all__ = ["EvalBroker", "ElasticSampler", "run_worker", "BrokerStatus"]
