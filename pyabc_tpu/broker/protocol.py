"""Wire protocol: length-prefixed pickle frames over a blocking socket.

Reference analog: the Redis sampler's key/queue schema
(``pyabc/sampler/redis_eps/cmd.py``: START/STOP/GENERATION counters and
result queues) — collapsed into seven request types against one broker:

- ``("hello", worker_id)``  -> ("work", gen, t, payload, batch, mode)
                             | ("wait",)   (mode: "dynamic" | "static")
- ``("get_slots", worker_id, gen, k)``
                            -> ("slots", start, stop) | ("done",)
- ``("results", worker_id, gen, [(slot, particle_bytes, accepted), ...])``
                            -> ("ok",) | ("done",)
- ``("heartbeat", worker_id, gen)`` -> ("ok",) | ("done",)
  (static-unit liveness probe: abandon a spinning quota unit once the
  generation is finalized)
- ``("bye", worker_id)``    -> ("ok",)   (graceful deregistration)
- ``("status",)``           -> ("status", BrokerStatus)
- ``("shutdown",)``         -> ("ok",)

Distributed-tracing extension (round 8) — trace-capable workers append
OPTIONAL trailing elements to the same request kinds; the broker answers
those (and only those) with its own monotonic clock appended, so every
exchange doubles as an NTP-style clock-offset sample with NO extra round
trips:

- ``("hello", worker_id, t1)``          -> work/wait reply + ``t_broker``
- ``("get_slots", wid, gen, k, t1)``    -> ("slots", start, stop, t_broker)
- ``("results", wid, gen, triples, trace)`` -> ("ok"|"done", t_broker)
  where ``trace`` is the piggybacked per-batch timing summary
  (:meth:`~pyabc_tpu.broker.worker.WorkerSpanRecorder.trace_payload`):
  worker-clock phase spans, the worker's current offset estimate +
  RTT-derived uncertainty, eval counters and its last error repr.
- ``("heartbeat", wid, gen, t1)``       -> ("ok"|"done", t_broker)
- ``("bye", worker_id, reason, trace)`` -> ("ok",)
  (``reason`` feeds BrokerStatus departed-worker bookkeeping; the final
  trace flushes ship spans that would otherwise be lost)

A PRE-TRACING worker omits the trailing elements and receives the exact
pre-round-8 reply shapes — old workers interoperate with the new broker
unchanged (asserted by ``tests/test_worker_tracing.py``).

Broker and worker ship together (same package); the frame format is not a
cross-version compatibility boundary.

Particles travel pre-pickled (``particle_bytes``) so the broker thread
never unpickles model-specific payloads while holding its lock.
"""
from __future__ import annotations

import pickle
import socket
import struct

_LEN = struct.Struct("!Q")
MAX_FRAME = 1 << 31  # 2 GiB sanity bound


def send_msg(sock: socket.socket, obj) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def recv_msg(sock: socket.socket):
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > MAX_FRAME:
        raise ValueError(f"frame too large: {n}")
    return pickle.loads(_recv_exact(sock, n))


def request(addr: tuple[str, int], obj, timeout: float = 30.0):
    """One connect-send-receive round trip (workers keep it simple and
    stateless: any broker restart or network blip costs one retry, not a
    corrupted session)."""
    with socket.create_connection(addr, timeout=timeout) as sock:
        send_msg(sock, obj)
        return recv_msg(sock)
