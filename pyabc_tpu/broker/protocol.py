"""Wire protocol: length-prefixed pickle frames over a blocking socket.

Reference analog: the Redis sampler's key/queue schema
(``pyabc/sampler/redis_eps/cmd.py``: START/STOP/GENERATION counters and
result queues) — collapsed into seven request types against one broker:

- ``("hello", worker_id)``  -> ("work", gen, t, payload, batch, mode)
                             | ("wait",)   (mode: "dynamic" | "static")
- ``("get_slots", worker_id, gen, k)``
                            -> ("slots", start, stop) | ("done",)
- ``("results", worker_id, gen, [(slot, particle_bytes, accepted), ...])``
                            -> ("ok",) | ("done",)
- ``("heartbeat", worker_id, gen)`` -> ("ok",) | ("done",)
  (static-unit liveness probe: abandon a spinning quota unit once the
  generation is finalized)
- ``("bye", worker_id)``    -> ("ok",)   (graceful deregistration)
- ``("status",)``           -> ("status", BrokerStatus)
- ``("shutdown",)``         -> ("ok",)

Distributed-tracing extension (round 8) — trace-capable workers append
OPTIONAL trailing elements to the same request kinds; the broker answers
those (and only those) with its own monotonic clock appended, so every
exchange doubles as an NTP-style clock-offset sample with NO extra round
trips:

- ``("hello", worker_id, t1)``          -> work/wait reply + ``t_broker``
- ``("get_slots", wid, gen, k, t1)``    -> ("slots", start, stop, t_broker)
- ``("results", wid, gen, triples, trace)`` -> ("ok"|"done", t_broker)
  where ``trace`` is the piggybacked per-batch timing summary
  (:meth:`~pyabc_tpu.broker.worker.WorkerSpanRecorder.trace_payload`):
  worker-clock phase spans, the worker's current offset estimate +
  RTT-derived uncertainty, eval counters and its last error repr.
- ``("heartbeat", wid, gen, t1)``       -> ("ok"|"done", t_broker)
- ``("bye", worker_id, reason, trace)`` -> ("ok",)
  (``reason`` feeds BrokerStatus departed-worker bookkeeping; the final
  trace flushes ship spans that would otherwise be lost)

A PRE-TRACING worker omits the trailing elements and receives the exact
pre-round-8 reply shapes — old workers interoperate with the new broker
unchanged (asserted by ``tests/test_worker_tracing.py``).

Broker and worker ship together (same package); the frame format is not a
cross-version compatibility boundary.

Particles travel pre-pickled (``particle_bytes``) so the broker thread
never unpickles model-specific payloads while holding its lock.

Resilience (round 9): :func:`request` runs under the shared
:class:`~pyabc_tpu.resilience.retry.RetryPolicy` — a connection drop or
broker restart costs a jittered backoff-and-retry instead of
propagating, with retries counted into
``pyabc_tpu_request_retries_total`` and reported per caller via
``on_retry``. Retrying a ``results`` message whose reply was lost is
SAFE because the broker's slot-level dedup (lease.py) drops the
duplicate delivery exactly-once. The ``protocol.request`` fault-plan
site sits INSIDE the retry loop, so injected drops exercise the same
path a real blip would.
"""
from __future__ import annotations

import pickle
import random
import socket
import struct

from ..observability import global_metrics
from ..observability.metrics import REQUEST_RETRIES_TOTAL
from ..resilience.faults import maybe_fault
from ..resilience.retry import DEFAULT_RETRY_POLICY, RetryPolicy

_LEN = struct.Struct("!Q")
MAX_FRAME = 1 << 31  # 2 GiB sanity bound

#: process-shared jitter source for request backoff (workers seed their
#: numpy RNG for SIMULATION reproducibility; transport jitter staying
#: uncorrelated across a pool is the point, so it is deliberately NOT
#: derived from the worker seed)
_JITTER_RNG = random.Random()


def send_msg(sock: socket.socket, obj) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def recv_msg(sock: socket.socket):
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > MAX_FRAME:
        raise ValueError(f"frame too large: {n}")
    return pickle.loads(_recv_exact(sock, n))


def request(addr: tuple[str, int], obj, timeout: float = 30.0,
            retry: RetryPolicy | None = None, on_retry=None):
    """One connect-send-receive round trip under the shared RetryPolicy.

    Workers stay simple and stateless: a broker restart or network blip
    costs a capped, jittered backoff-and-retry, not a corrupted session.
    ``retry=None`` uses :data:`~pyabc_tpu.resilience.retry.
    DEFAULT_RETRY_POLICY`; pass ``RetryPolicy(attempts=1)`` to disable.
    ``on_retry(retry_index, exc)`` lets callers count their own retries
    (the worker surfaces them in its trace summary -> BrokerStatus).
    """
    policy = retry if retry is not None else DEFAULT_RETRY_POLICY

    def _once():
        # fault-plan site INSIDE the retry loop: an injected drop is
        # retried exactly like a real one
        maybe_fault(
            "protocol.request",
            msg_kind=obj[0] if isinstance(obj, tuple) and obj else "",
        )
        with socket.create_connection(addr, timeout=timeout) as sock:
            send_msg(sock, obj)
            return recv_msg(sock)

    def _count(i, exc):
        global_metrics().counter(
            REQUEST_RETRIES_TOTAL,
            "broker round trips retried by the shared RetryPolicy",
        ).inc()
        if on_retry is not None:
            on_retry(i, exc)

    return policy.call(_once, retry_on=(ConnectionError, OSError),
                       rng=_JITTER_RNG, on_retry=_count)
