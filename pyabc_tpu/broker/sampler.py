"""ElasticSampler — the ABCSMC-facing side of the broker.

Reference parity: ``pyabc/sampler/redis_eps/sampler.py::
RedisEvalParallelSampler`` (static-scheduling variant; the look-ahead mode
is served by ABCSMC's own pipelined loops). Publishes each generation's
pickled ``simulate_one`` closure to the broker, blocks until enough
acceptances were DELIVERED, and applies the deterministic sort-by-slot
overshoot trim — the same unbiasedness invariant every other dynamic
sampler in this package honors (SURVEY.md §3.4).
"""
from __future__ import annotations

import pickle

import numpy as np

try:  # closures (simulate_one) need cloudpickle; plain functions don't
    import cloudpickle as _closure_pickle
except ImportError:  # pragma: no cover - cloudpickle is usually present
    _closure_pickle = pickle

from .broker import EvalBroker
from ..sampler.base import HostRecords, Sample, Sampler


class ElasticSampler(Sampler):
    """Farm host-model evaluations to elastic TCP workers.

    ``host``/``port``: broker bind address (port 0 = ephemeral; read
    ``.broker.address`` and hand it to ``abc-worker``). ``batch``: slots
    per worker round trip. Workers may join/leave at any time; at least
    one worker must be alive for a generation to finish —
    ``generation_timeout`` bounds the wait (None = forever).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 batch: int = 10,
                 generation_timeout: float | None = None,
                 wait_for_all_samples: bool = False,
                 scheduling: str = "dynamic"):
        """``wait_for_all_samples``: gather every in-flight evaluation
        before finalizing a generation (adaptive components then see an
        unbiased, complete record set — reference ``wait_for_all_samples``).
        ``scheduling``: 'dynamic' (evaluation-parallel slot handout,
        reference RedisEvalParallelSampler) or 'static' (fixed acceptance
        quotas per handed-out unit, reference RedisStaticSampler)."""
        super().__init__()
        self.batch = int(batch)
        self.generation_timeout = generation_timeout
        self.wait_for_all_samples = bool(wait_for_all_samples)
        if scheduling not in ("dynamic", "static"):
            raise ValueError(f"unknown scheduling {scheduling!r}")
        self.scheduling = scheduling
        self.broker = EvalBroker(host, port)

    @property
    def address(self) -> tuple[str, int]:
        return self.broker.address

    def sample_until_n_accepted(self, n, simulate_one, t, *,
                                max_eval=np.inf, all_accepted=False,
                                ana_vars=None) -> Sample:
        if hasattr(simulate_one, "host_simulate_one"):
            simulate_one = simulate_one.host_simulate_one
        payload = _closure_pickle.dumps(simulate_one)
        self.broker.start_generation(
            t if t is not None else -1, payload, n, max_eval=max_eval,
            all_accepted=all_accepted, batch=self.batch,
            wait_for_all=self.wait_for_all_samples,
            mode=self.scheduling,
        )
        triples = self.broker.wait(timeout=self.generation_timeout)

        sample = self.sample_factory()
        accepted, accepted_ids, records = [], [], []
        for slot, blob, acc in sorted(triples, key=lambda x: x[0]):
            particle = pickle.loads(blob)
            if sample.record_rejected:
                records.append(particle)
            if acc or all_accepted or particle.accepted:
                accepted.append(particle)
                accepted_ids.append(slot)
        self.nr_evaluations_ = len(triples)
        # deterministic overshoot trim by eval-slot id
        accepted = accepted[:n]
        accepted_ids = accepted_ids[:n]
        sample.accepted_particles = accepted
        sample.accepted_proposal_ids = np.asarray(accepted_ids)
        if sample.record_rejected and records:
            sample.host_all_records = HostRecords.from_particles(records)
        return sample

    def stop(self) -> None:
        self.broker.stop()
