"""ElasticSampler — the ABCSMC-facing side of the broker.

Reference parity: ``pyabc/sampler/redis_eps/sampler.py::
RedisEvalParallelSampler`` (static-scheduling variant; the look-ahead mode
is served by ABCSMC's own pipelined loops). Publishes each generation's
pickled ``simulate_one`` closure to the broker, blocks until enough
acceptances were DELIVERED, and applies the deterministic sort-by-slot
overshoot trim — the same unbiasedness invariant every other dynamic
sampler in this package honors (SURVEY.md §3.4).
"""
from __future__ import annotations

import logging
import pickle

import numpy as np

logger = logging.getLogger("ABC.Sampler")

try:  # closures (simulate_one) need cloudpickle; plain functions don't
    import cloudpickle as _closure_pickle
except ImportError:  # pragma: no cover - cloudpickle is usually present
    _closure_pickle = pickle

from .broker import EvalBroker
from ..sampler.base import HostRecords, Sample, Sampler


def _apply_delayed(p, accept_fn) -> bool:
    """Delayed acceptance for a look-ahead particle: the preliminary
    worker produced it without an accept test (epsilon unknown at
    simulation time); accept_fn may also RE-EVALUATE the distance under
    the generation's final weights. The weight already reflects the
    preliminary proposal actually used, so rejection just zeroes it."""
    acc = bool(accept_fn(p))
    p.accepted = acc
    p.preliminary = False
    if not acc:
        p.weight = 0.0
    return acc


class ElasticSampler(Sampler):
    """Farm host-model evaluations to elastic TCP workers.

    ``host``/``port``: broker bind address (port 0 = ephemeral; read
    ``.broker.address`` and hand it to ``abc-worker``). ``batch``: slots
    per worker round trip. Workers may join/leave at any time; at least
    one worker must be alive for a generation to finish —
    ``generation_timeout`` bounds the wait (None = forever).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 batch: int = 10,
                 generation_timeout: float | None = None,
                 wait_for_all_samples: bool = False,
                 scheduling: str = "dynamic",
                 look_ahead: bool = False,
                 look_ahead_frac: float = 0.5,
                 lease_timeout_s: float | None = None):
        """``wait_for_all_samples``: gather every in-flight evaluation
        before finalizing a generation (adaptive components then see an
        unbiased, complete record set — reference ``wait_for_all_samples``).
        ``scheduling``: 'dynamic' (evaluation-parallel slot handout,
        reference RedisEvalParallelSampler) or 'static' (fixed acceptance
        quotas per handed-out unit, reference RedisStaticSampler).
        ``look_ahead``: mid-generation cross-generation pipelining
        (reference look_ahead_delay_evaluation): once ``look_ahead_frac``
        of generation t's target is accepted, a PRELIMINARY t+1 proposal
        (built by the orchestrator from accepted-so-far particles) is
        pre-published; workers roll into it the instant t finalizes —
        zero idle during the orchestrator's persist/adapt — and t+1's
        delayed acceptance/weights are applied host-side against the
        final epsilon, with importance weights taken wrt the preliminary
        proposal actually used (no bias). The orchestrator enables this
        only for generation-invariant distances and plain uniform
        acceptance (ABCSMC._look_ahead_capable).
        ``lease_timeout_s`` (round 9): broker batch-lease deadline —
        handed-out work not delivered (and not refreshed by any contact
        from its owner) within this window requeues to live workers
        (None = the broker default). ``generation_timeout`` is now a
        LIVENESS deadline, not a hard stop: while at least one worker is
        alive the sampler extends it and lets the lease/redispatch
        machinery finish the generation on the survivors; TimeoutError
        is raised only once NO live worker remains."""
        super().__init__()
        self.batch = int(batch)
        self.generation_timeout = generation_timeout
        self.wait_for_all_samples = bool(wait_for_all_samples)
        if scheduling not in ("dynamic", "static"):
            raise ValueError(f"unknown scheduling {scheduling!r}")
        self.scheduling = scheduling
        self.look_ahead = bool(look_ahead)
        self.look_ahead_frac = float(look_ahead_frac)
        #: set by the orchestrator when the config is look-ahead-safe:
        #: fn(t_next, accepted_particles) -> pickled preliminary closure
        self.lookahead_builder = None
        #: set by the orchestrator each generation: fn(particle) -> bool
        #: (delayed acceptance against the now-known epsilon)
        self.lookahead_accept = None
        #: generation index served by the pre-published broker generation
        self._lookahead_t: int | None = None
        #: telemetry: per-generation head start (results already delivered
        #: when the orchestrator arrived) and adopted-generation count
        self.lookahead_head_starts: list[int] = []
        #: (slot, error repr) of evaluations a --catch worker converted
        #: into rejected error records during the LAST generation
        #: (reference: exceptions surfaced via rejected particles)
        self.error_records: list[tuple[int, str]] = []
        broker_kwargs = {}
        if lease_timeout_s is not None:
            broker_kwargs["lease_timeout_s"] = float(lease_timeout_s)
        self.broker = EvalBroker(host, port, **broker_kwargs)

    @property
    def address(self) -> tuple[str, int]:
        return self.broker.address

    def sample_until_n_accepted(self, n, simulate_one, t, *,
                                max_eval=np.inf, all_accepted=False,
                                ana_vars=None) -> Sample:
        adopt = (
            self.look_ahead and self._lookahead_t == t
            and self.lookahead_accept is not None
        )
        self._lookahead_t = None
        with self.tracer.span("broker.generation", t=int(t or 0),
                              n=int(n), adopted=bool(adopt)) as g_span:
            try:
                return self._sample_impl(n, simulate_one, t, max_eval,
                                         all_accepted, adopt, g_span)
            finally:
                # merge the generation's worker-side spans (shipped
                # piggybacked on result messages, offset-mapped by the
                # broker) onto the run tracer as per-worker
                # pseudo-threads — the elastic path's dark time then
                # decomposes in the same coverage accountant as the
                # fused path's
                self._merge_worker_spans()

    def _sample_impl(self, n, simulate_one, t, max_eval, all_accepted,
                     adopt, g_span) -> Sample:
        if not adopt:
            self.broker.cancel_pre_published()
            if hasattr(simulate_one, "host_simulate_one"):
                simulate_one = simulate_one.host_simulate_one
            payload = _closure_pickle.dumps(simulate_one)
            self.broker.start_generation(
                t if t is not None else -1, payload, n, max_eval=max_eval,
                all_accepted=all_accepted, batch=self.batch,
                wait_for_all=self.wait_for_all_samples,
                mode=self.scheduling,
            )
        accept_fn = self.lookahead_accept if adopt else None
        triples, tested = self._collect(n, t, max_eval, all_accepted,
                                        accept_fn, head_start=adopt,
                                        g_span=g_span)
        g_span.set(n_delivered=len(triples))
        if adopt and self.lookahead_head_starts:
            g_span.set(head_start=int(self.lookahead_head_starts[-1]))

        sample = self.sample_factory()
        accepted, accepted_ids, records = [], [], []
        self.error_records = []
        # `tested` is aligned with the broker's append-only delivery list
        # (every snapshot is a prefix of the final list), keyed by
        # DELIVERY INDEX — slots are NOT unique: a static-mode quota unit
        # ships every reject plus its accept under one slot id, so a
        # slot-keyed cache would shadow them with the last delivery
        for i, (slot, blob, acc) in sorted(
                enumerate(triples), key=lambda e: e[1][0]):
            if i < len(tested) and tested[i] is not None:
                # delayed acceptance already ran in _collect (unpickle +
                # distance recompute happen exactly once per delivery)
                particle, acc = tested[i]
            else:
                particle = pickle.loads(blob)
                if accept_fn is not None and \
                        getattr(particle, "error", None) is None:
                    acc = _apply_delayed(particle, accept_fn)
            if getattr(particle, "error", None) is not None:
                # a --catch worker converted a raising simulate_one into
                # this rejected error record; it counts as an evaluation
                # but carries no usable stats
                self.error_records.append((slot, particle.error))
                continue
            if sample.record_rejected:
                records.append(particle)
            if acc or all_accepted or (accept_fn is None
                                       and particle.accepted):
                accepted.append(particle)
                accepted_ids.append(slot)
        self.nr_evaluations_ = len(triples)
        if self.error_records:
            logger.warning(
                "%d evaluation(s) raised in workers and were recorded as "
                "rejected error records (first: %s)",
                len(self.error_records), self.error_records[0][1],
            )
        # deterministic overshoot trim by eval-slot id
        accepted = accepted[:n]
        accepted_ids = accepted_ids[:n]
        sample.accepted_particles = accepted
        sample.accepted_proposal_ids = np.asarray(accepted_ids)
        if sample.record_rejected and records:
            sample.host_all_records = HostRecords.from_particles(records)
        return sample

    def _merge_worker_spans(self) -> None:
        """Drain the broker's ingested worker spans onto the run tracer.

        Each span arrives offset-mapped onto the broker's clock — which
        IS the orchestrator tracer's timebase (broker and sampler share
        one process; both default to the observability SYSTEM_CLOCK) —
        on a ``worker:<id>`` pseudo-thread, carrying the clock-offset
        estimate and RTT-derived uncertainty it was merged with. With a
        NullTracer the drain still runs (the broker buffer stays
        bounded) but records nothing. Recovery spans ride along: the
        broker's ``recovery.redispatch`` windows (orphaned work waiting
        for a live worker) land on a ``recovery`` pseudo-thread so the
        gap accountant attributes self-healing time instead of
        reporting it dark."""
        spans = (self.broker.drain_worker_spans()
                 + self.broker.drain_recovery_spans())
        if not spans or not self.tracer.enabled:
            return
        for sp in spans:
            attrs = {k: v for k, v in sp.get("attrs", {}).items()
                     if k not in ("name", "start", "end", "thread")}
            self.tracer.record_span(
                sp["name"], sp["start"], sp["end"],
                thread=sp.get("thread"), **attrs,
            )

    def _note_poll_latency(self, gen: int, g_span) -> None:
        """Record how long the finished generation sat on the broker
        before this poll loop observed it — the orchestrator-poll slice
        of elastic dark time (the sampler sleeps 20 ms between
        snapshots; the broker finalizes asynchronously on a worker's
        results message)."""
        finished_at = self.broker.finished_at(gen)
        if finished_at is None:
            return
        now = self.tracer.clock.now()
        if now > finished_at:
            self.tracer.record_span("broker.poll_latency", finished_at,
                                    now, gen=int(gen))
            if g_span is not None:
                g_span.set(poll_latency_s=round(now - finished_at, 6))

    def _update_worker_gauges(self, status) -> None:
        """Broker queue-depth / worker-liveness / per-worker-throughput
        gauges (the ``pyabc_tpu_worker_*`` family, observability/
        metrics.py names)."""
        from ..observability.metrics import (
            per_worker_metric,
            WORKER_ALIVE_GAUGE,
            WORKER_CLOCK_OFFSET_GAUGE,
            WORKER_CLOCK_UNC_GAUGE,
            WORKER_KNOWN_GAUGE,
            WORKER_QUEUE_DEPTH_GAUGE,
        )

        m = self.metrics
        m.gauge(WORKER_KNOWN_GAUGE,
                "workers the broker has heard from").set(
            len(status.workers))
        m.gauge(WORKER_ALIVE_GAUGE,
                "workers heard from within the liveness window").set(
            sum(1 for w in status.workers.values()
                if not w.get("presumed_dead")))
        m.gauge(WORKER_QUEUE_DEPTH_GAUGE,
                "handed-out evaluation slots not yet delivered").set(
            max(status.n_eval_handed - status.n_results, 0))
        offsets = [w.get("clock_offset_s") for w in status.workers.values()
                   if w.get("clock_offset_s") is not None]
        uncs = [w.get("clock_offset_unc_s")
                for w in status.workers.values()
                if w.get("clock_offset_unc_s") is not None]
        if offsets:
            m.gauge(WORKER_CLOCK_OFFSET_GAUGE,
                    "largest |worker clock offset| vs the broker").set(
                max(abs(o) for o in offsets))
        if uncs:
            m.gauge(WORKER_CLOCK_UNC_GAUGE,
                    "largest worker clock-offset uncertainty").set(
                max(uncs))
        for wid, w in status.workers.items():
            joined = w.get("joined")
            age = (self.tracer.clock.now() - joined) if joined else 0.0
            if age > 0:
                m.gauge(
                    per_worker_metric("pyabc_tpu_worker_results_per_s",
                                      wid),
                    "delivered results per second since join",
                ).set(w.get("n_results", 0) / age)

    def _collect(self, n, t, max_eval, all_accepted, accept_fn, *,
                 head_start: bool, g_span=None) -> tuple[list, dict]:
        """Poll the broker until generation completion, applying delayed
        acceptance (look-ahead adoption) and/or pre-publishing the NEXT
        generation's preliminary closure once enough of this one is in.
        Generation-stamped throughout: a pre-published next generation
        auto-starts the instant this one finalizes, so completion may
        surface as a generation-id change rather than a done flag.

        Returns ``(triples, tested)`` where ``tested[i]`` is the
        (particle, accepted) pair for delivery ``triples[i]`` if it was
        already unpickled and delayed-accept-tested here (None
        otherwise) — the caller reuses them, so each delivery is
        unpickled and (possibly expensively) re-distanced exactly once.
        Indexing by delivery position is sound because the broker's
        result list is append-only: every snapshot is a prefix of the
        final list."""
        import time as _time

        clock = self.tracer.clock  # injected monotonic timebase
        deadline = (clock.now() + self.generation_timeout
                    if self.generation_timeout else None)
        inflight_gauge = self.metrics.gauge(
            "pyabc_tpu_broker_inflight_slots",
            "handed-out evaluation slots not yet delivered",
        )
        delivered_counter = self.metrics.counter(
            "pyabc_tpu_broker_results_delivered",
            "worker results delivered to the sampler",
        )
        prepublished = False
        gen0 = None
        # incremental acceptance over the broker's append-only result
        # list: each delivered triple is unpickled/tested exactly once
        # (rescanning the whole set per 20 ms poll was O(n) per poll —
        # quadratic over a generation)
        n_seen = 0
        n_acc = 0
        accepted_parts: list = []
        tested: list = []  # delivery-index aligned with the result list
        while True:
            triples, done, gen_now = self.broker.results_snapshot()
            if gen0 is None:
                gen0 = gen_now
                if head_start:
                    # overlap evidence: work already HANDED OUT (workers
                    # pull slots within ~ms of the auto-advance) by the
                    # time the orchestrator finished persist/adapt
                    self.lookahead_head_starts.append(max(
                        len(triples), self.broker.status().n_eval_handed
                    ))
            if gen_now != gen0:
                # finished and auto-advanced to the pre-published next gen
                self._note_poll_latency(gen0, g_span)
                last = self.broker.last_results(gen0)
                return (last if last is not None else []), tested
            need_particles = accept_fn is not None or (
                self.look_ahead and not prepublished
                and self.lookahead_builder is not None
            )
            for slot, blob, acc in triples[n_seen:]:
                if need_particles:
                    p = pickle.loads(blob)
                    if getattr(p, "error", None) is not None:
                        ok = False  # --catch error record: never accepted
                    elif accept_fn is not None:
                        ok = _apply_delayed(p, accept_fn)
                    else:
                        ok = bool(acc)
                    tested.append((p, ok))
                    if ok:
                        accepted_parts.append(p)
                else:
                    tested.append(None)
                    ok = bool(acc)
                if ok:
                    n_acc += 1
            if len(triples) > n_seen:
                delivered_counter.inc(len(triples) - n_seen)
            n_seen = len(triples)
            if self.metrics.enabled:
                status = self.broker.status()
                inflight_gauge.set(
                    max(status.n_eval_handed - n_seen, 0)
                )
                self._update_worker_gauges(status)
            if (self.look_ahead and not prepublished
                    and self.lookahead_builder is not None
                    and n_acc >= self.look_ahead_frac * n):
                with self.tracer.span("broker.prepublish", t_next=t + 1,
                                      n_builder=len(accepted_parts)):
                    payload_next = self.lookahead_builder(
                        t + 1, list(accepted_parts)
                    )
                if payload_next is not None:
                    self.broker.pre_publish(
                        t + 1, payload_next, n, batch=self.batch,
                        max_eval=max_eval,
                    )
                    self._lookahead_t = t + 1
                prepublished = True  # one attempt per generation
            if accept_fn is not None and not done \
                    and (n_acc >= n or len(triples) >= max_eval):
                # delayed-acceptance completion is the sampler's call
                self.broker.finish_generation()
                last = self.broker.last_results(gen0)
                return (last if last is not None else triples), tested
            if done:
                self._note_poll_latency(gen0, g_span)
                return triples, tested
            _time.sleep(0.02)
            if deadline and clock.now() > deadline:
                # graceful degradation (round 9): while ANY worker is
                # alive, the lease/redispatch machinery will finish the
                # generation on the survivors — extend the deadline and
                # keep collecting instead of killing the run. Only a
                # fully dead pool raises.
                status = self.broker.status()
                alive = [w for w, info in status.workers.items()
                         if not info.get("presumed_dead")]
                if not alive:
                    raise TimeoutError(
                        f"generation incomplete and no live workers "
                        f"remain: {status}"
                    )
                now = clock.now()
                self.tracer.record_span(
                    "recovery.timeout_extended", deadline, now,
                    thread="recovery", t=int(t or 0),
                    n_alive=len(alive),
                )
                from ..observability.metrics import (
                    TIMEOUT_EXTENSIONS_TOTAL,
                )

                self.metrics.counter(
                    TIMEOUT_EXTENSIONS_TOTAL,
                    "generation deadlines extended because live workers "
                    "remain",
                ).inc()
                logger.warning(
                    "generation %s exceeded generation_timeout=%.1fs; "
                    "%d live worker(s) remain — extending instead of "
                    "raising (leases requeue the dead workers' batches)",
                    t, self.generation_timeout, len(alive),
                )
                deadline = now + self.generation_timeout

    def cancel_look_ahead(self) -> None:
        """Retire any look-ahead state: drop a queued pre-publish, finalize
        an auto-started collect-only generation (workers would otherwise
        simulate the unused preliminary proposal FOREVER — collect-only
        generations have no self-completion), and clear the orchestrator
        hooks so a later, differently-configured run cannot adopt a stale
        proposal with an outdated epsilon."""
        self.broker.cancel_pre_published()
        if self._lookahead_t is not None:
            self.broker.finish_generation()
        self._lookahead_t = None
        self.lookahead_builder = None
        self.lookahead_accept = None

    def stop(self) -> None:
        self.cancel_look_ahead()
        self.broker.stop()
