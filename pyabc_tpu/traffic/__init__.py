"""pyabc_tpu.traffic — fleet-scale open-loop load generation (round 19).

The serving subsystem is chaos-tested tenant by tenant; this package
tests it the way production traffic will: SUSTAINED SEEDED ARRIVALS
(Poisson and burst processes) drawn from a tenant-spec zoo derived from
the scenario zoo, driven open-loop against a live
:class:`~pyabc_tpu.serving.scheduler.RunScheduler` — arrivals keep
coming whether or not the pool has caught up, exactly the regime where
bounded admission, Retry-After honesty, retention GC and fairness
either hold or visibly break.

- :mod:`.specs` — the tenant-spec zoo: weighted traffic classes
  (gaussian / Gillespie birth-death / SIR / K>1 selection; mixed
  populations, shard widths and History stores) and the seeded
  deterministic sampler over them;
- :mod:`.generator` — :class:`ArrivalSchedule` (precomputed seeded
  arrival process) and :class:`TrafficGenerator` (open-loop driver on
  the INJECTED clock — CLOCK001 — measuring admission latency, 429
  honesty against observed waits, p50/p99 time-to-posterior, and
  per-class fairness under churn).

Everything here is measurement and submission; no module in this
package constructs a run or touches a device (ISO001 stays with the
scheduler).
"""
from .generator import ArrivalSchedule, TrafficGenerator, percentile
from .specs import SPEC_PROFILES, TrafficClass, make_spec, spec_zoo

__all__ = [
    "ArrivalSchedule", "TrafficGenerator", "percentile",
    "SPEC_PROFILES", "TrafficClass", "make_spec", "spec_zoo",
]
