"""The tenant-spec zoo: weighted traffic classes over the scenario zoo.

A fleet does not submit one workload shape; it submits a MIX — cheap
gaussian smoke runs next to Gillespie birth-death tenants, SIR tenants
with real dynamics, K>1 model-selection pairs, occasional big sharded
populations — and the serving guarantees (fairness, bounded admission,
retention GC) only mean something measured against that mix. This
module pins the mix down as data: each :class:`TrafficClass` maps a
name to a :class:`~pyabc_tpu.serving.tenant.TenantSpec` template plus a
sampling weight, and :func:`spec_zoo` / :func:`make_spec` turn a seed
into a reproducible spec draw.

Two profiles, both deterministic in the seed:

- ``smoke``  — CI-sized (~40 tenants in ~60 s on forced-8-device CPU):
  tiny populations, few generations, gaussian-dominant but every model
  family represented so the lane exercises all MODEL_BUILDERS paths;
- ``full``   — the bench/fleet mix for the 1000-tenant churn run:
  wider population spread, sharded big tenants, columnar-store tenants
  alongside plain sqlite ones.

Pure data + seeded draws; no clocks, no devices, no run construction.
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..serving.tenant import MODEL_BUILDERS, TenantSpec


@dataclass(frozen=True)
class TrafficClass:
    """One weighted workload shape in the zoo.

    ``weight`` is the relative arrival probability within a profile;
    ``pops``/``gens`` are the discrete choices a draw picks from
    uniformly (mixed shapes WITHIN a class keep the compiled-kernel
    cache honest — same model, several pad widths).
    """

    name: str
    model: str
    weight: float
    pops: tuple[int, ...]
    gens: tuple[int, ...]
    fused_generations: int = 1
    #: shard count (power of two >= 2) for a sub-mesh lease; None =
    #: an unsharded width-1 tenant (packable per chip)
    sharded: int | None = None
    store: str = "rows"
    params: dict = field(default_factory=dict)

    def __post_init__(self):
        if self.model not in MODEL_BUILDERS:
            raise ValueError(
                f"unknown model {self.model!r}; "
                f"known: {sorted(MODEL_BUILDERS)}")
        if self.weight <= 0:
            raise ValueError("weight must be positive")


#: CI-sized mix: everything small, every model family touched. The
#: gaussian classes dominate (they are the cheapest to simulate) so the
#: ~60 s smoke job spends its budget on SCHEDULING pressure — many
#: arrivals, churn, GC — not on simulation depth.
_SMOKE = (
    TrafficClass("gauss-small", "gaussian", weight=4.0,
                 pops=(100, 200), gens=(3, 4)),
    TrafficClass("gauss-fused", "gaussian", weight=2.0,
                 pops=(200,), gens=(4,), fused_generations=2),
    TrafficClass("bd-small", "gillespie_bd", weight=1.0,
                 pops=(100,), gens=(3,)),
    TrafficClass("sir-small", "sir", weight=1.0,
                 pops=(100,), gens=(3,)),
    TrafficClass("select-small", "selection_pair", weight=1.0,
                 pops=(150,), gens=(3,)),
)

#: The fleet mix for the 1000-tenant churn run: population spread wide
#: enough to hit several pad widths per model, a sharded big-gaussian
#: class (width>1 leases contending with the width-1 packing), and
#: columnar-store tenants so retention GC has Parquet files to delete.
_FULL = _SMOKE + (
    TrafficClass("gauss-big-sharded", "gaussian", weight=0.5,
                 pops=(800, 1600), gens=(6, 8), fused_generations=2,
                 sharded=4),
    TrafficClass("gauss-columnar", "gaussian", weight=1.5,
                 pops=(200, 400), gens=(4, 6), store="columnar"),
    TrafficClass("bd-columnar", "gillespie_bd", weight=0.5,
                 pops=(200,), gens=(4,), store="columnar"),
)

SPEC_PROFILES: dict[str, tuple[TrafficClass, ...]] = {
    "smoke": _SMOKE,
    "full": _FULL,
}


def spec_zoo(profile: str = "smoke") -> tuple[TrafficClass, ...]:
    """The traffic classes of ``profile`` (``smoke`` or ``full``)."""
    try:
        return SPEC_PROFILES[profile]
    except KeyError:
        raise ValueError(
            f"unknown traffic profile {profile!r}; "
            f"known: {sorted(SPEC_PROFILES)}") from None


def make_spec(cls: TrafficClass, seed: int,
              data_seed: int | None = None) -> TenantSpec:
    """Instantiate one spec draw from a class, deterministically in
    ``seed`` (which also seeds the run itself)."""
    rng = np.random.default_rng(seed)
    pop = int(cls.pops[int(rng.integers(len(cls.pops)))])
    gens = int(cls.gens[int(rng.integers(len(cls.gens)))])
    return TenantSpec(
        model=cls.model,
        population_size=pop,
        generations=gens,
        seed=int(seed),
        fused_generations=cls.fused_generations,
        data_seed=int(data_seed if data_seed is not None else seed),
        sharded=cls.sharded,
        store=cls.store,
        params=dict(cls.params),
    )


def draw_class(classes: tuple[TrafficClass, ...],
               rng: np.random.Generator) -> TrafficClass:
    """Weighted class draw (the generator's per-arrival choice)."""
    weights = np.asarray([c.weight for c in classes], np.float64)
    idx = int(rng.choice(len(classes), p=weights / weights.sum()))
    return classes[idx]
