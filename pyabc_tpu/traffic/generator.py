"""Open-loop traffic generation against a live :class:`RunScheduler`.

Closed-loop load tests (submit, wait, submit) measure the system at the
rate the SYSTEM chooses; production tenants arrive at the rate THEY
choose. :class:`ArrivalSchedule` precomputes a seeded arrival process
(Poisson gaps or periodic bursts) over the spec zoo, and
:class:`TrafficGenerator` replays it open-loop: an arrival whose due
time has passed is submitted NOW whether or not the pool has caught up,
and a 429 answer schedules a retry exactly ``Retry-After`` later — the
generator honors the hint, which is precisely what lets it measure
whether the hint was honest.

Measured, all on the injected clock (CLOCK001 — this module is in the
abc-lint INSTRUMENTED set):

- admission latency — wall time spent inside ``submit()`` per arrival
  (p99 guards the scheduler lock under churn);
- 429 honesty — per rejected arrival, ``observed_wait / first_hint``
  where observed_wait runs from the first rejection to eventual
  admission; an honest hint keeps the ratio near 1, the bench lane
  bounds its p90;
- time-to-posterior — ``finished_at - submitted_at`` per completed
  tenant (p50/p99 + the ``pyabc_tpu_time_to_posterior_seconds``
  histogram the scheduler feeds);
- fairness — within each traffic class, max/min accepted-particle
  throughput over SERVICE time (started -> finished; a tenant's queue
  wait reflects arrival order, not the scheduler's treatment) across
  completed tenants (cross-class ratios compare apples to oranges;
  within-class they expose starvation).

The generator only submits and observes — it never constructs runs or
touches devices (ISO001 stays with the scheduler).
"""
from __future__ import annotations

import heapq
import time
from dataclasses import dataclass, field

import numpy as np

from ..observability import global_metrics
from ..observability.metrics import (
    RETRY_HONESTY_HISTOGRAM,
    TRAFFIC_ARRIVALS_TOTAL,
    TRAFFIC_REJECTIONS_TOTAL,
    Histogram,
)
from ..serving.admission import AdmissionRejectedError
from ..serving.tenant import TERMINAL_STATES
from .specs import TrafficClass, draw_class, make_spec, spec_zoo


def percentile(samples, q: float) -> float:
    """Percentile over raw samples through the SHARED log2-bucket
    estimator (:meth:`Histogram.quantile`, round 22) — lane numbers and
    SLO burn numbers now come from one estimator instead of the old
    numpy interpolation, so a lane p99 and the SLO engine's bucketed
    p99 can only disagree by bucket resolution, never by method."""
    if not samples:
        return float("nan")
    h = Histogram("_lane_percentile_scratch")
    for s in samples:
        h.observe(float(s))
    return h.quantile(q / 100.0)


@dataclass
class Arrival:
    """One scheduled submission and everything observed about it."""

    idx: int
    due_s: float
    cls: TrafficClass
    seed: int
    tenant_id: str | None = None
    spec: object | None = None  # the TenantSpec actually drawn
    admit_latency_s: float | None = None
    first_reject_at: float | None = None
    first_hint_s: float | None = None
    rejections: int = 0
    admitted_at: float | None = None
    dropped: str | None = None  # non-retryable rejection reason
    ttp_s: float | None = None  # time-to-posterior once terminal
    run_s: float | None = None  # service time (started -> finished)
    final_state: str | None = None


class ArrivalSchedule:
    """A precomputed, seeded arrival process over the spec zoo.

    Precomputing (rather than drawing as the clock runs) keeps the
    process independent of scheduler timing: the same seed yields the
    same arrivals whether the pool keeps up or drowns.
    """

    def __init__(self, arrivals: list[Arrival]):
        self.arrivals = sorted(arrivals, key=lambda a: a.due_s)

    def __len__(self):
        return len(self.arrivals)

    @property
    def horizon_s(self) -> float:
        return self.arrivals[-1].due_s if self.arrivals else 0.0

    @classmethod
    def poisson(cls, n: int, rate_hz: float, seed: int = 0,
                profile: str = "smoke",
                classes=None) -> "ArrivalSchedule":
        """``n`` arrivals with exponential inter-arrival gaps at
        ``rate_hz``, classes drawn by zoo weight (or from an explicit
        ``classes`` tuple overriding the profile)."""
        rng = np.random.default_rng(seed)
        classes = classes if classes is not None else spec_zoo(profile)
        t = 0.0
        arrivals = []
        for i in range(int(n)):
            t += float(rng.exponential(1.0 / float(rate_hz)))
            arrivals.append(Arrival(
                idx=i, due_s=t, cls=draw_class(classes, rng),
                seed=int(rng.integers(0, 2**31 - 1))))
        return cls(arrivals)

    @classmethod
    def burst(cls, n_bursts: int, burst_size: int, interval_s: float,
              seed: int = 0, profile: str = "smoke",
              classes=None) -> "ArrivalSchedule":
        """``n_bursts`` bursts of ``burst_size`` simultaneous arrivals
        every ``interval_s`` — the worst case for admission latency and
        Retry-After honesty."""
        rng = np.random.default_rng(seed)
        classes = classes if classes is not None else spec_zoo(profile)
        arrivals = []
        idx = 0
        for b in range(int(n_bursts)):
            due = b * float(interval_s)
            for _ in range(int(burst_size)):
                arrivals.append(Arrival(
                    idx=idx, due_s=due, cls=draw_class(classes, rng),
                    seed=int(rng.integers(0, 2**31 - 1))))
                idx += 1
        return cls(arrivals)


class TrafficGenerator:
    """Replay an :class:`ArrivalSchedule` against a scheduler, honoring
    Retry-After on 429s, and collect the fleet-level measurements."""

    def __init__(self, sched, schedule: ArrivalSchedule, *,
                 metrics=None, max_retries: int = 50):
        self.sched = sched
        self.clock = sched.clock
        self.schedule = schedule
        self.metrics = metrics if metrics is not None else global_metrics()
        self.max_retries = int(max_retries)
        self._epoch = self.clock.now()
        # (fire_at, tiebreak, arrival) heaps; retries share the heap so
        # a retry due before a fresh arrival goes first
        self._heap: list[tuple[float, int, Arrival]] = []
        self._tie = 0
        for a in schedule.arrivals:
            self._push(self._epoch + a.due_s, a)
        self._pending: dict[str, Arrival] = {}  # tenant_id -> live
        self._done: list[Arrival] = []
        self._arrivals_total = self.metrics.counter(
            TRAFFIC_ARRIVALS_TOTAL,
            "Traffic-generator submission attempts")
        self._rejections_total = self.metrics.counter(
            TRAFFIC_REJECTIONS_TOTAL,
            "Traffic-generator admission rejections")

    def _push(self, fire_at: float, arrival: Arrival) -> None:
        heapq.heappush(self._heap, (fire_at, self._tie, arrival))
        self._tie += 1

    # ------------------------------------------------------------ driving
    def step(self) -> int:
        """Submit every arrival/retry due at ``clock.now()`` and poll
        live tenants for terminal states; returns submissions made."""
        now = self.clock.now()
        n = 0
        while self._heap and self._heap[0][0] <= now:
            _, _, arrival = heapq.heappop(self._heap)
            self._submit(arrival)
            n += 1
        self._poll()
        return n

    def done(self) -> bool:
        return not self._heap and not self._pending

    def run(self, budget_s: float, poll_s: float = 0.05) -> None:
        """Drive :meth:`step` until every arrival is terminal or the
        budget expires (real sleeps; the measurements ride the injected
        clock, which for a live run is SYSTEM_CLOCK)."""
        deadline = self.clock.now() + float(budget_s)
        while not self.done() and self.clock.now() < deadline:
            self.step()
            time.sleep(poll_s)
        self._poll()

    def abort_pending(self) -> int:
        """Quiesce: drop every unfired arrival/retry and cancel every
        still-live tenant (phase boundaries in the bench lane — the
        churn phase must release the pool before a drained probe can
        measure it). Returns the number of cancellations requested;
        RUNNING tenants stop at their next chunk boundary, so the pool
        frees progressively, not instantly."""
        self._heap.clear()
        n = 0
        for tid in list(self._pending):
            try:
                if self.sched.cancel(tid):
                    n += 1
            except AttributeError:
                break  # a scheduler without cancel(): nothing to do
        self._poll()
        return n

    # --------------------------------------------------------- internals
    def _submit(self, arrival: Arrival) -> None:
        if arrival.spec is None:
            arrival.spec = make_spec(arrival.cls, seed=arrival.seed)
        spec = arrival.spec
        self._arrivals_total.inc()
        t0 = self.clock.now()
        try:
            tenant = self.sched.submit(spec)
        except AdmissionRejectedError as exc:
            self._rejections_total.inc()
            arrival.rejections += 1
            now = self.clock.now()
            if arrival.first_reject_at is None:
                arrival.first_reject_at = now
                arrival.first_hint_s = exc.retry_after_s
            if (exc.retry_after_s is None
                    or arrival.rejections > self.max_retries):
                arrival.dropped = exc.reason
                self._done.append(arrival)
                return
            self._push(now + float(exc.retry_after_s), arrival)
            return
        now = self.clock.now()
        arrival.admit_latency_s = now - t0
        arrival.admitted_at = now
        arrival.tenant_id = tenant.id
        if arrival.first_reject_at is not None and arrival.first_hint_s:
            # Retry-After honesty lands in the shared registry the
            # moment it is knowable (admission after a 429), so the
            # retry_honesty SLO burns on live traffic, not on report()
            self.metrics.histogram(
                RETRY_HONESTY_HISTOGRAM,
                "observed wait after a 429 divided by the first "
                "Retry-After hint (1.0 = perfectly honest)",
            ).observe((now - arrival.first_reject_at)
                      / arrival.first_hint_s)
        self._pending[tenant.id] = arrival

    def _poll(self) -> None:
        for tid in list(self._pending):
            tenant = self.sched.get(tid)
            arrival = self._pending[tid]
            if tenant is None:  # evicted before we sampled it
                arrival.final_state = "evicted"
            elif tenant.state in TERMINAL_STATES:
                arrival.final_state = tenant.state
                if tenant.finished_at is not None:
                    arrival.ttp_s = (tenant.finished_at
                                     - tenant.submitted_at)
                    started = getattr(tenant, "started_at", None)
                    # service time excludes the queue: fairness must
                    # compare the scheduler's treatment, not arrival
                    # order (under churn ttp is dominated by backlog)
                    arrival.run_s = (
                        tenant.finished_at - started
                        if started is not None else arrival.ttp_s)
            else:
                continue
            del self._pending[tid]
            self._done.append(arrival)

    def assert_slos(self, slo_engine=None, *, allow=()) -> list:
        """Assert the fleet's declared SLOs are not burning (round 22).

        Forces a sample on ``slo_engine`` (default: the scheduler's own
        :class:`~pyabc_tpu.observability.SloEngine`) and raises
        ``AssertionError`` naming every alerting SLO not in ``allow``
        — the traffic lane's post-drain gate. Returns the (filtered)
        list of alerting SLO names, empty on success."""
        engine = (slo_engine if slo_engine is not None
                  else getattr(self.sched, "slo", None))
        if engine is None:
            return []
        engine.sample(force=True)
        allowed = {str(a) for a in allow}
        firing = sorted(
            s["name"] for s in engine.snapshot()["slos"]
            if s["alerting"] and s["name"] not in allowed)
        if firing:
            raise AssertionError(
                f"SLOs burning after traffic drain: {firing}")
        return firing

    # ------------------------------------------------------------ results
    def report(self) -> dict:
        """The fleet measurement: admission latency, 429 honesty,
        time-to-posterior percentiles, per-class fairness."""
        done = list(self._done)
        # admission latency and honesty are known the moment an arrival
        # is ADMITTED — live tenants count, only completion metrics wait
        landed = done + list(self._pending.values())
        admit_lat = [a.admit_latency_s for a in landed
                     if a.admit_latency_s is not None]
        ttp = [a.ttp_s for a in done if a.ttp_s is not None]
        honesty = [
            (a.admitted_at - a.first_reject_at) / a.first_hint_s
            for a in landed
            if (a.admitted_at is not None
                and a.first_reject_at is not None
                and a.first_hint_s)
        ]
        by_class: dict[str, list[float]] = {}
        for a in done:
            service = a.run_s if a.run_s else a.ttp_s
            if (a.final_state == "completed" and service
                    and service > 0 and a.spec is not None):
                pps = (a.spec.population_size
                       * a.spec.generations) / service
                by_class.setdefault(a.cls.name, []).append(pps)
        fairness = {}
        for name, pps in by_class.items():
            if len(pps) >= 2 and min(pps) > 0:
                fairness[name] = max(pps) / min(pps)
        states: dict[str, int] = {}
        for a in done:
            key = "dropped" if a.dropped else (a.final_state or "pending")
            states[key] = states.get(key, 0) + 1
        return {
            "arrivals": len(self.schedule),
            "submitted": len([a for a in landed
                              if a.admitted_at is not None]),
            "pending": len(self._pending),
            "rejections": sum(a.rejections for a in landed),
            "dropped": len([a for a in done if a.dropped]),
            "states": states,
            "admission_latency_s": {
                "p50": percentile(admit_lat, 50),
                "p99": percentile(admit_lat, 99),
                "n": len(admit_lat),
            },
            "honesty_ratio": {
                "p50": percentile(honesty, 50),
                "p90": percentile(honesty, 90),
                "max": max(honesty) if honesty else float("nan"),
                "n": len(honesty),
            },
            "time_to_posterior_s": {
                "p50": percentile(ttp, 50),
                "p99": percentile(ttp, 99),
                "n": len(ttp),
            },
            "fairness_max_ratio": (max(fairness.values())
                                   if fairness else float("nan")),
            "fairness_by_class": fairness,
            "completed_by_class": {k: len(v)
                                   for k, v in by_class.items()},
        }
