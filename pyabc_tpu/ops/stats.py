"""Device-side (jnp, traceable) weighted statistics.

These are the in-kernel counterparts of ``pyabc_tpu.core.weighted_statistics``
for use inside jitted generation steps and shard_map'd collectives — e.g.
normalizing importance weights with a psum, or computing a weighted quantile
of distances without leaving the device.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def weighted_quantile(points, weights, alpha):
    """Step-function weighted quantile (matches host semantics).

    Fully traceable: sort + cumsum + searchsorted. ``alpha`` may be a scalar
    or vector of quantile levels.
    """
    order = jnp.argsort(points)
    p = points[order]
    cum = jnp.cumsum(weights[order])
    cdf = cum / cum[-1]
    idx = jnp.clip(jnp.searchsorted(cdf, alpha), 0, p.shape[0] - 1)
    return p[idx]


def weighted_mean(points, weights, axis=None):
    return jnp.sum(points * weights, axis=axis) / jnp.sum(weights, axis=axis)


def weighted_var(points, weights, axis=None):
    mu = weighted_mean(points, weights, axis=axis)
    if axis is not None:
        mu = jnp.expand_dims(mu, axis)
    return weighted_mean((points - mu) ** 2, weights, axis=axis)


def weighted_std(points, weights, axis=None):
    return jnp.sqrt(weighted_var(points, weights, axis=axis))


def effective_sample_size(weights):
    s = jnp.sum(weights)
    return s * s / jnp.sum(weights * weights)


def normalize_log_weights(log_w, mask=None):
    """exp-normalize masked log-weights to sum to 1 (stable).

    An entirely-masked (or all -inf) input returns all zeros instead of NaN,
    so an empty-acceptance round surfaces as zero mass, not NaN poisoning.
    """
    if mask is not None:
        log_w = jnp.where(mask, log_w, -jnp.inf)
    m = jnp.max(log_w)
    safe_m = jnp.where(jnp.isfinite(m), m, 0.0)
    w = jnp.exp(log_w - safe_m)
    total = jnp.sum(w)
    return jnp.where(total > 0, w / jnp.where(total > 0, total, 1.0), 0.0)


def logsumexp_weighted(log_terms, axis=-1):
    return jax.scipy.special.logsumexp(log_terms, axis=axis)
