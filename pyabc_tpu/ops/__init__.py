from . import pack, stats

__all__ = ["pack", "stats"]
