from . import stats

__all__ = ["stats"]
