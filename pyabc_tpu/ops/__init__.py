from . import pack, select, stats

__all__ = ["pack", "select", "stats"]
