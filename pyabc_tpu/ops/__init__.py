from . import health, pack, select, stats

__all__ = ["health", "pack", "select", "stats"]
