"""Device-side numerical/statistical health word (ISSUE 6 tentpole).

The fused multigen loop runs whole generations inside a ``lax.scan`` —
the host sees nothing until a chunk's packed fetch lands, so a NaN in
the carry, an ESS-collapsed population, or a non-PSD proposal covariance
silently degrades EVERY following generation of the chunk (PAPER.md's
reference design does these checks host-side per generation; the
device-resident architecture bypassed them). This module restores the
checks DEVICE-NATIVELY: a per-generation int32 bitmask ("health word")
computed from values the kernel already holds, shipped as one extra
scalar per generation on the existing packed fetch — ZERO additional
blocking syncs (acceptance criterion: ``SyncLedger`` counts unchanged).

Bit layout (host decode + recovery mapping live in
:mod:`pyabc_tpu.resilience.health`):

====================== ======================================================
bit                    condition
====================== ======================================================
``BIT_NAN_THETA``      non-finite accepted theta rows
``BIT_NAN_WEIGHT``     non-finite normalized importance weights
``BIT_NAN_DISTANCE``   non-finite accepted distances
``BIT_WEIGHT_ZERO``    accepted rows exist but carry zero total weight
                       (all log-weights -inf: the population is unusable)
``BIT_ESS_FLOOR``      ESS of the accepted weights below
                       ``ess_floor * n_target`` (NaN ESS also trips it:
                       the comparison is ``~(ess >= floor)``)
``BIT_ACC_COLLAPSE``   acceptance rate below the configured floor
``BIT_EPS_STALL``      relative epsilon improvement below ``rtol`` for
                       ``window`` consecutive generations (carried counter)
``BIT_PSD_FAIL``       non-finite / zero-mass fitted proposal params —
                       the carry-INPUT params actually proposed from this
                       generation, or the just-refit ones (a Cholesky that
                       stayed non-finite through the jitter escalation of
                       ``transition.util.device_chol_guarded``)
``BIT_EPS_NONFINITE``  non-finite epsilon used or produced
====================== ======================================================

Everything here is traceable jnp math on data already resident in the
generation step — reductions of O(n_cap * d) bools, noise next to the
refit's matmuls.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

HEALTH_OK = 0
BIT_NAN_THETA = 1 << 0
BIT_NAN_WEIGHT = 1 << 1
BIT_NAN_DISTANCE = 1 << 2
BIT_WEIGHT_ZERO = 1 << 3
BIT_ESS_FLOOR = 1 << 4
BIT_ACC_COLLAPSE = 1 << 5
BIT_EPS_STALL = 1 << 6
BIT_PSD_FAIL = 1 << 7
BIT_EPS_NONFINITE = 1 << 8

#: host-readable names, in bit order (resilience.health re-exports)
BIT_NAMES = (
    "nan_theta", "nan_weight", "nan_distance", "weight_zero",
    "ess_floor", "acc_collapse", "eps_stall", "psd_fail",
    "eps_nonfinite",
)


def _bit(cond, bit: int):
    return jnp.where(cond, jnp.int32(bit), jnp.int32(0))


def ess_of(w_norm, k_mask):
    """Effective sample size of normalized weights over the accepted
    mask (Kish ESS; weights are already normalized to sum 1 over the
    mask, so ESS = 1 / sum w^2). NaN weights yield NaN — callers detect
    that with ``~(ess >= floor)``."""
    w = jnp.where(k_mask, w_norm, 0.0)
    return 1.0 / jnp.maximum(jnp.sum(w * w), 1e-38)


def params_unhealthy(trans_params, fitted):
    """True when any FITTED model's proposal params contain non-finite
    values or an all-zero resampling weight vector — the in-kernel
    surface of a corrupted carry (``nan_poison`` / ``cov_corrupt``) or a
    Cholesky that survived jitter escalation non-finite. Never-fitted
    placeholder params are zeros by construction and are excluded."""
    bad = jnp.asarray(False)
    for m, params in enumerate(trans_params):
        finite = jnp.asarray(True)
        for leaf in jax.tree.leaves(params):
            finite = finite & jnp.all(jnp.isfinite(leaf))
        w = params.get("weights")
        zero_w = (jnp.sum(w) <= 0.0) if w is not None else False
        bad = bad | (fitted[m] & (~finite | zero_w))
    return bad


def population_bits(res, k_mask, w_norm, d_new, n_acc, *,
                    ess_floor: float, n_target, acc_rate,
                    acc_floor: float):
    """Health bits derived from one generation's accepted population."""
    theta_bad = ~jnp.all(jnp.isfinite(
        jnp.where(k_mask[:, None], res["theta"], 0.0)))
    return population_bits_cols(
        theta_bad=theta_bad, k_mask=k_mask, w_norm=w_norm, d_new=d_new,
        n_acc=n_acc, ess_floor=ess_floor, n_target=n_target,
        acc_rate=acc_rate, acc_floor=acc_floor,
    )


def population_bits_cols(*, theta_bad, k_mask, w_norm, d_new, n_acc,
                         ess_floor: float, n_target, acc_rate,
                         acc_floor: float):
    """Population health bits from column data + a precomputed theta
    flag. The sharded multigen kernel keeps theta rows device-local and
    reduces their finiteness check across shards (a one-bit collective);
    every other bit derives from the gathered scalar columns — the exact
    same math as the single-device :func:`population_bits`."""
    w_masked = jnp.where(k_mask, w_norm, 0.0)
    w_bad = ~jnp.all(jnp.isfinite(w_masked))
    d_bad = ~jnp.all(jnp.isfinite(jnp.where(k_mask, d_new, 0.0)))
    # normalize_log_weights maps an all(-inf) row to all-zeros: accepted
    # rows with zero total mass are a degenerate population, not a NaN
    w_zero = (n_acc > 0) & (jnp.sum(w_masked) <= 0.0)
    ess = ess_of(w_norm, k_mask)
    # ~(>=) instead of (<): a NaN ESS must trip the floor too
    ess_bad = ~(ess >= ess_floor * jnp.maximum(
        n_target, 1).astype(jnp.float32))
    acc_bad = (acc_floor > 0.0) & (acc_rate < acc_floor)
    word = (
        _bit(theta_bad, BIT_NAN_THETA)
        | _bit(w_bad, BIT_NAN_WEIGHT)
        | _bit(d_bad, BIT_NAN_DISTANCE)
        | _bit(w_zero, BIT_WEIGHT_ZERO)
        | _bit(ess_bad, BIT_ESS_FLOOR)
        | _bit(acc_bad, BIT_ACC_COLLAPSE)
    )
    return word, ess


def eps_stall_update(eps_prev, eps_g, stall_count, *, window: int,
                     rtol: float):
    """Carried epsilon-stall recursion: relative improvement of this
    generation's epsilon vs the previous one; ``window`` consecutive
    sub-``rtol`` improvements set the bit. ``window <= 0`` disables
    (fixed epsilon schedules legitimately never improve). A non-finite
    previous epsilon (fresh run seed) counts as full improvement."""
    if window <= 0:
        return jnp.int32(0), jnp.zeros((), jnp.int32)
    impr = jnp.where(
        jnp.isfinite(eps_prev),
        (eps_prev - eps_g) / jnp.maximum(jnp.abs(eps_prev), 1e-30),
        1.0,
    )
    stalled = impr < rtol
    count_next = jnp.where(stalled, stall_count + 1, 0).astype(jnp.int32)
    return _bit(count_next >= window, BIT_EPS_STALL), count_next


def generation_health(*, res, k_mask, w_norm, d_new, n_acc, n_target,
                      acc_rate, trans_params, trans_next, fitted,
                      fitted_next, eps_g, eps_next, eps_prev, stall_count,
                      ess_floor: float, acc_floor: float,
                      stall_window: int, stall_rtol: float):
    """The full per-generation health word + updated stall state.

    ``trans_params``/``fitted`` are the carry-INPUT proposal params the
    generation actually sampled from (a poisoned carry is detected the
    FIRST generation it is used, not after a refit launders it);
    ``trans_next``/``fitted_next`` are the just-refit ones (a refit that
    produced non-finite factors is detected before the next generation
    proposes from it). Returns ``(word, ess, eps_prev_next,
    stall_count_next)``.
    """
    word, ess = population_bits(
        res, k_mask, w_norm, d_new, n_acc, ess_floor=ess_floor,
        n_target=n_target, acc_rate=acc_rate, acc_floor=acc_floor,
    )
    psd_bad = params_unhealthy(trans_params, fitted) \
        | params_unhealthy(trans_next, fitted_next)
    word = word | _bit(psd_bad, BIT_PSD_FAIL)
    eps_bad = ~jnp.isfinite(eps_g) | ~jnp.isfinite(eps_next)
    word = word | _bit(eps_bad, BIT_EPS_NONFINITE)
    stall_bit, stall_next = eps_stall_update(
        eps_prev, eps_g, stall_count, window=stall_window,
        rtol=stall_rtol,
    )
    word = word | stall_bit
    return word, ess, eps_g, stall_next


# --------------------------------------------------------- fault injection

#: numeric-corruption fault kinds (resilience.faults ``device.carry``
#: site): each corrupts the dispatched chunk's input carry so a specific
#: guard is exercised deterministically on CPU
POISON_KINDS = ("nan_poison", "cov_corrupt", "weight_zero")


def poison_carry(carry, kind: str):
    """Corrupt a fused-chunk carry IN PLACE OF the clean one (the clean
    ref stays valid for rollback). Traceable jnp ops only — the poison
    rides the normal dispatch, adding no sync.

    - ``nan_poison``: NaN into the first fitted ancestor theta — the
      proposal logpdf mixes it into EVERY lane's importance weight, the
      chunk-wide silent-NaN propagation the tentpole exists to catch;
    - ``cov_corrupt``: NaN into the Cholesky factor(s) — every proposal
      draw goes non-finite (the non-PSD / corrupted-covariance shape);
    - ``weight_zero``: zero the ancestor resampling weights — a
      weight-degenerate carry (the ESS-collapse shape at its limit).
    """
    if kind not in POISON_KINDS:
        raise ValueError(f"unknown poison kind {kind!r} ({POISON_KINDS})")
    carry = list(carry)
    trans = list(carry[0])
    params = dict(trans[0])
    if kind == "nan_poison":
        params["thetas"] = jnp.asarray(params["thetas"]).at[0, 0].set(
            jnp.nan)
        if "thetas_c" in params:
            params["thetas_c"] = jnp.asarray(
                params["thetas_c"]).at[0, 0].set(jnp.nan)
    elif kind == "cov_corrupt":
        key = "chols" if "chols" in params else "chol"
        params[key] = jnp.full_like(jnp.asarray(params[key]), jnp.nan)
    else:  # weight_zero
        params["weights"] = jnp.zeros_like(jnp.asarray(params["weights"]))
    trans[0] = params
    carry[0] = tuple(trans)
    return tuple(carry)
