"""On-device predictor fits for learned summary statistics (ISSUE 20).

The Fearnhead-Prangle transform s(x) = E[theta | x] is learned by
regressing accepted thetas on raw summary statistics. The host parity
layer (``predictor/predictor.py``) fits per generation with numpy /
optax; this module holds the TRACEABLE twins that run INSIDE the
multigen kernel at the chunk boundary, so the fitted parameters ride
the chunk carry as plain device operands and the per-run sync count
stays at ``chunks + O(1)``:

1. :func:`ridge_fit` — the weighted ridge normal-equations solve of
   ``LinearPredictor.fit`` on masked reservoir rows. It returns the
   SAME ``{"W", "b", "mu", "sd"}`` pytree ``device_params()`` produces,
   so a host-seeded carry and a kernel-refit carry have identical
   structure (checkpoint resume and the dispatch engine never see the
   difference).
2. :func:`mlp_fit_steps` — a fixed number of full-batch Adam steps on
   the ``MLPPredictor`` tanh network, warm-started from the carried
   layer stack (the host fit restarts from a seeded init each
   generation; warm-starting is the documented deviation that makes
   the in-kernel fit a bounded-cost scan instead of a cold optimize).
3. :func:`linear_bound_prepare` — the transformed-space prefix bound
   of the segmented early-reject engine: for a fitted LINEAR transform
   the partial feature vector accumulated over a trajectory prefix
   determines a sound lower bound on the final transformed distance
   via null-space projectors of the remaining segments' coefficient
   rows (see the function docstring for the math and its soundness
   argument).

Everything here is pure jax-traceable math with no host callbacks: the
fits execute under ``lax.cond`` at the boundary generation of a chunk,
in both the unsharded scan and the sharded kernel's replicated
post-collective section.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

#: floor below which a standardization scale is treated as constant
#: (mirrors ``predictor._standardize_fit``: sd <= 1e-12 -> 1.0)
SD_FLOOR = 1e-12

#: relative eigenvalue threshold under which a direction of the
#: remaining-segments Gram is treated as unreachable (numerically null).
#: Directions within float noise of zero eigenvalue are counted into the
#: bound; genuinely tiny-but-positive eigenvalues are excluded (kept
#: reachable), which only makes the bound SMALLER — the conservative,
#: sound direction for early rejection.
NULL_EIG_RTOL = 1e-6


def masked_standardize(x, mask):
    """Per-column (mu, sd) of the masked rows of ``x`` — the traceable
    twin of ``predictor._standardize_fit`` (biased /n std, sd floor).

    ``x``: (n, S); ``mask``: (n,) bool. Returns ((S,), (S,)) float32.
    """
    m = mask.astype(jnp.float32)
    n = jnp.maximum(jnp.sum(m), 1.0)
    mu = jnp.sum(x * m[:, None], axis=0) / n
    var = jnp.sum(((x - mu) ** 2) * m[:, None], axis=0) / n
    sd = jnp.sqrt(var)
    sd = jnp.where(sd > SD_FLOOR, sd, 1.0)
    return mu, sd


def ridge_fit(x, y, w, mask, alpha: float):
    """Weighted ridge normal-equations fit on masked rows — the traceable
    twin of ``LinearPredictor.fit``.

    ``x``: (n, S) raw sum-stat rows; ``y``: (n, d) (padded) theta rows;
    ``w``: (n,) non-negative particle weights; ``mask``: (n,) bool row
    eligibility (the reservoir's accepted prefix). Rows outside the mask
    contribute exactly nothing. Returns the ``LinearPredictor``
    ``device_params()`` pytree ``{"W": (S, d), "b": (d,), "mu": (S,),
    "sd": (S,)}`` in float32.

    Math (identical to the host fit, which runs in float64): weights
    renormalized to sum n, inputs standardized by masked (mu, sd),
    ``W = (Xs' diag(w) Xs + alpha I)^-1 Xs' diag(w) (y - ym)``,
    ``b = ym`` the weighted target mean. ``alpha > 0`` keeps the system
    positive definite for any mask population.
    """
    x = x.astype(jnp.float32)
    y = y.astype(jnp.float32)
    S = x.shape[1]
    m = mask.astype(jnp.float32)
    n = jnp.maximum(jnp.sum(m), 1.0)
    mu, sd = masked_standardize(x, mask)
    xs = ((x - mu) / sd) * m[:, None]
    w = jnp.maximum(w, 0.0) * m
    w = w * n / jnp.maximum(jnp.sum(w), 1e-30)
    A = xs.T @ (xs * w[:, None]) + alpha * jnp.eye(S, dtype=jnp.float32)
    ym = (w @ y) / n
    B = xs.T @ (w[:, None] * ((y - ym) * m[:, None]))
    W = jnp.linalg.solve(A, B)
    return {"W": W, "b": ym, "mu": mu, "sd": sd}


def _mlp_forward(layers, x):
    """Traceable twin of ``MLPPredictor._forward`` (tanh hidden, linear
    head) over a batch ``x``: (n, S)."""
    h = x
    for layer in layers[:-1]:
        h = jnp.tanh(h @ layer["w"] + layer["b"])
    last = layers[-1]
    return h @ last["w"] + last["b"]


def mlp_fit_steps(params, x, y, w, mask, *, lr: float, n_steps: int):
    """A bounded number of full-batch Adam steps on the MLP predictor,
    warm-started from the carried ``{"layers", "mu", "sd", "ymu",
    "ysd"}`` pytree; returns the same structure.

    Input/target standardization is refit from the masked rows each
    boundary (like the host fit); the layer stack continues from its
    carried values instead of a fresh seeded init — the warm-start
    deviation documented in the module header. Adam is implemented
    inline (optax is an optional dependency of the HOST fit only; the
    kernel must stay importable without it).
    """
    x = x.astype(jnp.float32)
    y = y.astype(jnp.float32)
    m = mask.astype(jnp.float32)
    mu, sd = masked_standardize(x, mask)
    ymu, ysd = masked_standardize(y, mask)
    xs = ((x - mu) / sd) * m[:, None]
    ys = ((y - ymu) / ysd) * m[:, None]
    n = jnp.maximum(jnp.sum(m), 1.0)
    wts = jnp.maximum(w, 0.0) * m
    wts = wts / jnp.maximum(jnp.sum(wts) / n, 1e-30)
    layers = params["layers"]

    def loss_fn(p):
        pred = _mlp_forward(p, xs)
        return jnp.sum(wts[:, None] * (pred - ys) ** 2) / (n * ys.shape[1])

    b1, b2, eps = 0.9, 0.999, 1e-8
    zeros = jax.tree.map(jnp.zeros_like, layers)

    def step(carry, i):
        p, mo, ve = carry
        g = jax.grad(loss_fn)(p)
        mo = jax.tree.map(lambda a, b: b1 * a + (1 - b1) * b, mo, g)
        ve = jax.tree.map(lambda a, b: b2 * a + (1 - b2) * b * b, ve, g)
        t = (i + 1).astype(jnp.float32)
        corr1 = 1.0 - b1 ** t
        corr2 = 1.0 - b2 ** t
        p = jax.tree.map(
            lambda pp, a, b: pp - lr * (a / corr1)
            / (jnp.sqrt(b / corr2) + eps),
            p, mo, ve,
        )
        return (p, mo, ve), ()

    (layers, _, _), _ = jax.lax.scan(
        step, (layers, zeros, zeros),
        jnp.arange(n_steps, dtype=jnp.int32),
    )
    return {"layers": layers, "mu": mu, "sd": sd, "ymu": ymu, "ysd": ysd}


def keep_if_finite(new, old):
    """``(params, ok)`` where ``params`` is ``new`` if every leaf of
    ``new`` is all-finite, else ``old`` — the kernel's blown-fit guard.

    A float32 normal-equations solve can go non-finite when the Gram
    matrix is ill-conditioned relative to ``alpha`` (measured: S=128
    correlated stats at alpha=1e-6). Accepting such params would poison
    every subsequent distance and kill the run via the health engine;
    keeping the previous boundary's transform merely skips one refit.
    ``ok`` is the scalar bool so callers can gate values derived from
    the new params (e.g. recomputed reservoir distances) on the same
    decision.
    """
    ok = jnp.bool_(True)
    for leaf in jax.tree.leaves(new):
        ok = ok & jnp.all(jnp.isfinite(leaf))
    guarded = jax.tree.map(
        lambda nw, od: jnp.where(ok, nw, od), new, old)
    return guarded, ok


def linear_bound_prepare(dist_params, imap_np: np.ndarray):
    """Per-generation precompute of the TRANSFORMED-space prefix bound
    (segmented early reject under a fitted linear learned transform,
    p = 2).

    With a linear transform the weighted transformed difference is
    ``u = (x - x0)^T A`` where ``A[c, :] = w * W[c, :] / sd[c]`` (the
    standardization shift cancels between x and x0). A trajectory
    prefix P determines ``v = sum_{c in P} (x_c - x0_c) A[c, :]``; the
    unknown remainder ``r`` lies in the row span of the REMAINING
    segments' coefficient rows, so

        min_r ||v + r||^2  =  v^T P_j v,

    with ``P_j`` the orthogonal projector onto the null space of the
    suffix Gram ``G_j = A_rest^T A_rest`` after ``j`` segments — an
    EXACT lower bound on the final squared distance, computable from
    the prefix alone. Early segments usually leave ``G_j`` full-rank
    (``P_j = 0``: no retirement, trivially sound); as segments fold in
    the null space grows until ``P_{n_seg} = I`` recovers the full
    distance. Eigendirections within :data:`NULL_EIG_RTOL` of zero are
    treated as null (float noise of a true zero); small-but-real
    eigenvalues stay reachable, which only SHRINKS the bound — the
    conservative side. The engine's ``BOUND_RTOL`` slack band guards
    the comparison itself, and a surviving lane always gets the exact
    final accept test.

    ``imap_np`` is the STATIC (n_segments, seg_size) emission map; the
    suffix masks are resolved at trace time, the Grams/eigh run on
    device once per generation (C' x C' with C' = number of learned
    features — a few, so the eigh cost is noise).

    Returns ``{"At": (S, C'), "proj": (n_segments + 1, C', C')}``.
    """
    ssp = dist_params["ss"]
    w = dist_params["w"]
    W = ssp["W"]
    At = (W / ssp["sd"][:, None]) * jnp.asarray(w, jnp.float32)[None, :]
    n_seg = imap_np.shape[0]
    projs = []
    for j in range(n_seg + 1):
        cols = imap_np[j:].reshape(-1)
        rows = At[jnp.asarray(cols, jnp.int32)] if cols.size else None
        if rows is None:
            G = jnp.zeros((At.shape[1], At.shape[1]), jnp.float32)
        else:
            G = rows.T @ rows
        lam, Q = jnp.linalg.eigh(G)
        lam_max = jnp.maximum(lam[-1], 1e-30)
        null = (lam <= NULL_EIG_RTOL * lam_max).astype(jnp.float32)
        projs.append((Q * null[None, :]) @ Q.T)
    return {"At": At, "proj": jnp.stack(projs)}


def linear_bound_fns(rtol: float, out_dim: int):
    """The ``{"init", "step", "exceeds"}`` closures of the transformed
    prefix bound (see :func:`linear_bound_prepare` for the math and the
    prepared-parameter layout). The accumulator is a flat
    ``(out_dim + 1,)`` float32 row — the partial feature vector plus
    the folded-segment count (exact in f32 for any real segment count),
    so the engine's existing broadcast/select lane machinery applies
    unchanged."""

    def init():
        return jnp.zeros((out_dim + 1,), jnp.float32)

    def step(acc, vals, idx, x0, bp):
        contrib = (vals - x0[idx]) @ bp["At"][idx]
        return jnp.concatenate([acc[:-1] + contrib, acc[-1:] + 1.0])

    def exceeds(acc, threshold, bp):
        j = jnp.clip(acc[-1].astype(jnp.int32), 0, bp["proj"].shape[0] - 1)
        v = acc[:-1]
        q = v @ (bp["proj"][j] @ v)
        return q > (threshold * (1.0 + rtol)) ** 2

    return {"init": init, "step": step, "exceeds": exceeds}
