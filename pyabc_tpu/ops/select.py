"""Threshold-based neighbor selection — the sub-sort-complexity
replacement for ``lax.top_k`` at large k (ISSUE 3 tentpole #2).

Motivation (BASELINE.md rounds 5-6, SURVEY §7.3.4): the LocalTransition
in-kernel refit ran ``lax.top_k(-sq, k)`` per row block with
``k = k_fraction * n`` — at pop 16384 / k 4096 that is close to a full
row sort EVERY generation, and XLA's sort lowers to serial-ish vector
code on TPU while the rest of the refit is MXU matmuls. A kth-nearest-
neighbor set, however, is fully described by a per-row RADIUS: the
smallest r with ``|{j : d_ij <= r}| >= k``. Radius search needs only
comparisons and sums (VPU-friendly, no data-dependent permutation):

1. :func:`radius_bisect` — fixed-iteration bisection on r per row over
   a (rows, n) squared-distance tile. Monotone counts make every
   iteration a masked sum; the returned upper bound always satisfies
   the count constraint. A static ``stride`` bisects on a strided
   candidate subsample (count target scaled accordingly) so the
   O(iters * rows * n) count work shrinks by the stride — the radius
   picks up a small sampling error (documented below).
2. :func:`compact_within_radius` — the masked gather: candidates with
   ``sq <= r`` compact left into a static ``(rows, k_cap)`` index
   buffer via a per-row cumsum rank (no sort), plus the per-row count.

Documented deviations from exact top-k (all bounded, tested in
``tests/test_select.py``; exact parity holds below the top_k-fallback
cutoff where the caller keeps the sort):

- **ties / bisection resolution**: candidates exactly at the radius are
  all included (top_k breaks ties by index); with ``n_iters`` ~ the f32
  mantissa width the radius is tight to ~1 ulp, so this only differs on
  genuinely duplicated distances.
- **candidate stride** (``stride > 1``): the whole selection — radius
  bisection, mask, compaction, returned indices — runs on a
  ``[::stride]`` candidate subsample with count target
  ``ceil(k / stride)``: the downstream covariance becomes an unbiased
  mean over ~k/stride uniformly-subsampled within-radius neighbors
  (relative second-moment noise ~``sqrt(stride / k)``, ~6% at k 4096 /
  stride 4). The caller divides by the REALIZED count, so only the
  neighborhood sample, not the estimator's normalization, is perturbed
  — and every post-distance cost scales by 1/stride.
- **capacity clip**: at most ``k_cap`` indices are kept (lowest
  candidate index first); rows whose realized count exceeds the static
  buffer lose the tail — the same truncation top_k's static k applies.

Also here: :func:`apply_rowwise_blocked`, the dynamic-occupancy blocked
map used by the incremental Cholesky path (tentpole #3) — run an
expensive per-row function ONLY over rows flagged changed, in fixed-size
blocks with a data-dependent trip count, scattering results into the
carried previous outputs.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

#: below this static k bound, ``lax.top_k`` is cheap and EXACT — callers
#: (LocalTransition.device_fit selection="auto") keep the sort there
DEFAULT_TOPK_CUTOFF = 1024
#: bisection iterations: ~f32 mantissa width, so the radius is resolved
#: to ~1 ulp of the distance scale
DEFAULT_BISECT_ITERS = 26


def default_stride(n: int) -> int:
    """Candidate-subsample stride for the bisection count sums: 1 (exact
    counts) up to moderate n, 4 beyond — the count noise is ~sqrt(stride/k)
    relative, negligible exactly where k is large."""
    return 4 if n >= 8192 else 1


def radius_bisect(sq, k, *, n_iters: int = DEFAULT_BISECT_ITERS):
    """Per-row neighbor radius via fixed-iteration bisection.

    ``sq``: (rows, n) squared distances; excluded candidates carry +inf.
    ``k``: scalar (traced ok) target neighbor count per row.

    Returns ``r`` (rows,) such that ``count(sq <= r) >= k``: the
    bisection keeps the count-feasible upper bound, whose initial value
    (the row max over finite entries) is always feasible when the row
    has >= k finite candidates — the caller's k rule guarantees that.
    """
    sub = sq
    k_t = jnp.asarray(k, sq.dtype)
    finite = jnp.isfinite(sub)
    hi0 = jnp.max(jnp.where(finite, sub, -jnp.inf), axis=1)
    hi0 = jnp.where(jnp.isfinite(hi0), hi0, 0.0)
    lo0 = jnp.zeros_like(hi0)

    def body(_, lohi):
        lo, hi = lohi
        mid = 0.5 * (lo + hi)
        cnt = jnp.sum(sub <= mid[:, None], axis=1).astype(sq.dtype)
        ok = cnt >= k_t
        return (jnp.where(ok, lo, mid), jnp.where(ok, mid, hi))

    _, r = jax.lax.fori_loop(0, n_iters, body, (lo0, hi0))
    return r


def compact_within_radius(sq, r, k_cap: int):
    """The masked gather: left-compact candidate indices with
    ``sq <= r`` into a static ``(rows, k_cap)`` buffer, in candidate
    order, via a per-row cumsum rank (no sort, no top_k).

    Returns ``(idx, cnt)``: ``idx[i, p]`` is the p-th selected candidate
    of row i (0-filled past ``cnt[i]`` — callers mask positions with
    ``arange(k_cap) < cnt``), ``cnt`` the per-row selected count CLIPPED
    to the buffer capacity.
    """
    rows, n = sq.shape
    mask = sq <= r[:, None]
    rank = jnp.cumsum(mask.astype(jnp.int32), axis=1) - 1
    pos = jnp.where(mask & (rank < k_cap), rank, k_cap)
    idx = jnp.zeros((rows, k_cap), jnp.int32).at[
        jnp.arange(rows, dtype=jnp.int32)[:, None], pos
    ].set(jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[None, :],
                           (rows, n)), mode="drop")
    cnt = jnp.minimum(jnp.sum(mask, axis=1), k_cap)
    return idx, cnt


def threshold_neighbors(sq, k, k_cap: int, *,
                        n_iters: int = DEFAULT_BISECT_ITERS,
                        stride: int = 1):
    """Bisection + masked gather in one call: the drop-in replacement for
    ``lax.top_k(-sq, k_cap)`` on a (rows, n) distance tile.

    With ``stride > 1`` the WHOLE selection — bisection counts, mask,
    rank compaction, returned indices — runs on the ``[::stride]``
    candidate subsample with the count target ``ceil(k / stride)`` and a
    ``ceil(k_cap / stride)`` buffer: the caller's covariance then
    averages over a uniform subsample of the within-radius neighborhood
    (an unbiased second-moment estimate from ~k/stride points; the
    documented large-k tolerance). Every post-distance cost scales by
    1/stride. Returns ``(idx, cnt, r)`` with ``idx`` mapped back to
    full-resolution candidate indices."""
    if stride > 1:
        sub = sq[:, ::stride]
        k_sub = (k + stride - 1) // stride
        k_cap_sub = -(-k_cap // stride)
    else:
        sub = sq
        k_sub = k
        k_cap_sub = k_cap
    r = radius_bisect(sub, k_sub, n_iters=n_iters)
    idx, cnt = compact_within_radius(sub, r, k_cap_sub)
    if stride > 1:
        idx = idx * stride
    return idx, cnt, r


def apply_rowwise_blocked(fn, changed, prev_outs, *row_inputs,
                          block: int = 1024):
    """Run ``fn`` only over rows flagged ``changed``, in fixed-size
    blocks with a DATA-DEPENDENT trip count (``lax.while_loop``), and
    scatter its outputs over ``prev_outs`` — unchanged rows keep their
    previous values and, crucially, their blocks never execute.

    ``fn(*blocks) -> tuple_of_blocks``: takes each of ``row_inputs``
    gathered to ``(block, ...)`` and returns a tuple matching
    ``prev_outs`` leading dims ``(block, ...)``. ``changed`` is (n,)
    bool. Returns ``(outs, n_changed)``.

    This is the execution shape XLA cannot reach from a plain
    ``jnp.where`` gate (both sides of a select are computed): the
    while_loop body runs ``ceil(n_changed / block)`` times at runtime,
    so a mostly-unchanged refit costs O(changed) rather than O(n).
    """
    n = changed.shape[0]
    block = min(block, n)
    rank = jnp.cumsum(changed.astype(jnp.int32)) - 1
    pos = jnp.where(changed, rank, n)
    buf = jnp.zeros((n,), jnp.int32).at[pos].set(
        jnp.arange(n, dtype=jnp.int32), mode="drop"
    )
    n_changed = jnp.sum(changed.astype(jnp.int32))
    n_blocks = (n_changed + block - 1) // block
    outs0 = tuple(prev_outs)

    def cond(state):
        b = state[0]
        return b < n_blocks

    def body(state):
        b, outs = state
        start = b * block
        ids = jax.lax.dynamic_slice(buf, (start,), (block,))
        res = fn(*(x[ids] for x in row_inputs))
        in_range = (start + jnp.arange(block, dtype=jnp.int32)) < n_changed
        w = jnp.where(in_range, ids, n)
        outs = tuple(
            o.at[w].set(r, mode="drop") for o, r in zip(outs, res)
        )
        return (b + 1, outs)

    _, outs = jax.lax.while_loop(
        cond, body, (jnp.zeros((), jnp.int32), outs0)
    )
    return outs, n_changed
