"""Shard math for the sharded fused sampling path (ISSUE 9 tentpole).

The sharded multigen kernel (``DeviceContext.multigen_kernel(...,
sharded=n)``) splits the population axis over the one-axis device mesh:
each device proposes its own block of lanes (the *lane-key reduction*:
global lane ``i`` keeps the exact PRNG key it has on one device — device
``d`` simply owns the contiguous block ``[d*B_loc, (d+1)*B_loc)``) and
compacts acceptances into its own reservoir shard of ``n_cap /
n_shards`` rows. This module holds the small, host-and-trace-shared
arithmetic of that layout:

- :func:`shard_quota` — how many accepted rows each shard owes a
  generation (uneven populations spread the remainder over the leading
  shards);
- :func:`merge_index` — the static gather that reorders the
  shard-blocked reservoir layout ``[shard0 rows | shard1 rows | ...]``
  into the dense accepted-row order the host's slot-ordered trim
  expects (it rides the packed-fetch program, so the row all-gather
  happens exactly once per chunk);
- :func:`shard_mask` — the traceable global accepted-row mask over the
  shard-blocked layout.

Everything the kernel does per generation across shards is a
*scalar-column* collective (distances, log-weights, model ids, per-shard
counters — a few bytes per row); the row payloads (theta, sum stats)
cross devices only through :func:`merge_index` at chunk boundaries and
through the in-kernel chunk-end proposal refit.
"""
from __future__ import annotations

import numpy as np


def shard_quota_host(n_target: int, n_shards: int) -> np.ndarray:
    """Per-shard accepted-row quotas for a generation target (host side).

    ``n_target`` rows spread as evenly as possible: the first
    ``n_target % n_shards`` shards take one extra row, so any population
    size works on any mesh width (the uneven-shard contract)."""
    base, extra = divmod(int(n_target), int(n_shards))
    return np.asarray(
        [base + (1 if s < extra else 0) for s in range(int(n_shards))],
        np.int32,
    )


def shard_quota(n_target, n_shards: int):
    """Traceable twin of :func:`shard_quota_host` for an in-kernel
    (possibly traced) generation target: ``(n_shards,)`` int32."""
    import jax.numpy as jnp

    base = n_target // n_shards
    extra = n_target % n_shards
    return (base + (jnp.arange(n_shards, dtype=jnp.int32) < extra)
            ).astype(jnp.int32)


def merge_index(n_keep: int, n_shards: int, cap_loc: int) -> np.ndarray:
    """Static gather indices merging the shard-blocked reservoir into
    dense accepted-row order.

    Shard ``s`` keeps its first ``quota[s]`` rows at global (gathered)
    positions ``[s*cap_loc, s*cap_loc + quota[s])``; the packed fetch
    gathers them back-to-back so the host sees ``n_keep`` dense rows,
    exactly like the single-device reservoir."""
    quota = shard_quota_host(n_keep, n_shards)
    if int(quota.max(initial=0)) > cap_loc:
        raise ValueError(
            f"shard quota {int(quota.max())} exceeds per-shard reservoir "
            f"capacity {cap_loc} (n_keep={n_keep}, n_shards={n_shards})"
        )
    parts = [
        s * cap_loc + np.arange(quota[s], dtype=np.int32)
        for s in range(n_shards)
    ]
    return np.concatenate(parts) if parts else np.zeros(0, np.int32)


def shard_mask(nacc_sh, quota_sh, n_shards: int, cap_loc: int):
    """Traceable accepted-row mask over the shard-blocked global layout:
    row ``j`` (shard ``j // cap_loc``, offset ``j % cap_loc``) is
    accepted iff its offset is below both its shard's quota and its
    shard's actual acceptance count."""
    import jax.numpy as jnp

    j = jnp.arange(n_shards * cap_loc)
    sh = j // cap_loc
    off = j % cap_loc
    lim = jnp.minimum(nacc_sh, quota_sh)
    return off < lim[sh]
