"""Segmented simulation: the early-reject execution protocol (ISSUE 15).

On the expensive scenario simulators (tau-leap Gillespie, network SIR,
ODE families) the fused kernel's dominant cost is the proposal loop,
where every lane runs the FULL trajectory (hundreds of ``lax.scan``
steps) before the accept/reject decision — yet for p-norm distances the
partial distance accumulated over a trajectory PREFIX is monotone
non-decreasing, so a lane whose prefix bound already exceeds the
generation epsilon is provably rejected and every remaining step of its
simulation is discardable work. In late generations acceptance sits in
the few-percent range, which makes nearly all device time provably
wasted.

This module holds the protocol + pure math of the fix; the execution
engine (the segment-inner proposal loop with mid-flight lane refill)
lives in ``inference/util.py::DeviceContext._generation_while_seg``.

The protocol (:class:`SegmentedSim`): a model factors its simulator into

- ``init(key, theta) -> carry`` — allocate the trajectory state (the
  carry typically stores the sim key; per-step keys derive from it via
  ``fold_in`` so a segment is reproducible in isolation);
- ``step(carry, seg_idx) -> (carry, (seg_size,) f32)`` — advance one
  fixed-length segment and emit that segment's summary-statistic
  block. ``seg_idx`` MUST enter only as data (dynamic indexing /
  ``fold_in`` tags) — the engine vmaps lanes sitting at DIFFERENT
  segment indices through one step program, and a ``lax.switch`` over
  ``seg_idx`` would execute every branch per lane;
- ``layout`` — the emit order: per segment, for each named statistic,
  the next ``sizes[name] / n_segments`` entries of its time series.

``full_sim_from_segments`` synthesizes the ordinary ``sim(key, theta)``
dict simulator by scanning the segment chain — the classic (unsegmented)
kernel, the host oracle and the segmented engine therefore execute the
IDENTICAL per-step math on identical keys, which is what makes the
early-reject ON vs OFF populations bit-comparable.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class SegmentedSim:
    """Segmented-simulation protocol of a :class:`~pyabc_tpu.model.JaxModel`.

    ``layout`` is a tuple of ``(stat_name, per_segment_length)`` pairs in
    the order ``step`` emits them; summing the lengths gives
    ``seg_size`` and each stat's full series has
    ``per_segment_length * n_segments`` entries.
    """

    n_segments: int
    init: Callable
    step: Callable
    layout: tuple

    @property
    def seg_size(self) -> int:
        return int(sum(per for _name, per in self.layout))


def carry_struct_for(seg: SegmentedSim, dim: int):
    """(shape, dtype) pytree of the protocol's carry for a dim-parameter
    model — the K>1 uniformity gate compares these across models."""
    key = jax.eval_shape(lambda: jax.random.key(0))
    theta = jax.ShapeDtypeStruct((dim,), jnp.float32)
    return jax.eval_shape(seg.init, key, theta)


def index_map_for(seg: SegmentedSim, spec) -> np.ndarray:
    """``(n_segments, seg_size)`` int32 map from each segment's emitted
    block to its positions in the spec's FLAT sum-stat vector.

    The flat vector concatenates stats by sorted name (``SumStatSpec``),
    so a multi-channel time series is NOT contiguous in trajectory
    order — segment ``j`` of channel ``c`` lands at
    ``offsets[c] + j*per_seg .. + (j+1)*per_seg``. The engine gathers a
    lane's row with its CURRENT (data-dependent) segment index, which is
    why this is a precomputed array and not control flow.
    """
    rows = []
    for j in range(seg.n_segments):
        cols = []
        for name, per in seg.layout:
            if name not in spec.offsets:
                raise KeyError(
                    f"segment layout names unknown stat {name!r} "
                    f"(spec has {spec.names})"
                )
            if per * seg.n_segments != spec.sizes[name]:
                raise ValueError(
                    f"stat {name!r}: {seg.n_segments} segments x {per} "
                    f"per segment != spec size {spec.sizes[name]}"
                )
            off = spec.offsets[name] + j * per
            cols.append(np.arange(off, off + per))
        rows.append(np.concatenate(cols))
    out = np.stack(rows).astype(np.int32)
    if out.shape != (seg.n_segments, seg.seg_size):
        raise ValueError("segment layout does not tile the spec")
    return out


def full_sim_from_segments(seg: SegmentedSim):
    """Synthesize the ordinary dict simulator ``sim(key, theta)`` from the
    segment chain (one ``lax.scan`` over segments). Both execution modes
    of a segmented model run THIS chain — the classic kernel through the
    scan, the early-reject engine step by step — so a proposal that runs
    to completion produces identical statistics either way."""

    def sim(key, theta):
        carry0 = seg.init(key, theta)

        def body(c, j):
            c, vals = seg.step(c, j)
            return c, vals

        _, out = jax.lax.scan(body, carry0,
                              jnp.arange(seg.n_segments, dtype=jnp.int32))
        res = {}
        col = 0
        for name, per in seg.layout:
            # (n_segments, per) -> the stat's full series in time order
            res[name] = out[:, col:col + per].reshape(-1)
            col += per
        return res

    return sim


def uniform_protocol_reason(models) -> str | None:
    """Why a model family cannot run ONE segmented engine program (None
    = uniform). The engine switches the segment step over the model id
    per lane, so every model must declare the same segment count, the
    same emitted block size and an identical carry structure."""
    segs = [getattr(m, "segmented", None) for m in models]
    if any(s is None for s in segs):
        missing = [m.name for m, s in zip(models, segs) if s is None]
        return (f"model(s) {missing} declare no segmented-simulation "
                f"protocol (JaxModel(segmented=...))")
    ref = segs[0]
    if ref.n_segments < 2:
        return "n_segments < 2 leaves nothing to retire early"
    for m, s in zip(models[1:], segs[1:]):
        if s.n_segments != ref.n_segments or s.seg_size != ref.seg_size:
            return (f"model {m.name!r} segments "
                    f"({s.n_segments}x{s.seg_size}) differ from "
                    f"{models[0].name!r} ({ref.n_segments}x{ref.seg_size})")
        if tuple(s.layout) != tuple(ref.layout):
            return (f"model {m.name!r} emit layout differs from "
                    f"{models[0].name!r}")
    try:
        structs = [
            jax.tree.map(
                lambda x: (tuple(x.shape), str(x.dtype)),
                carry_struct_for(s, m.space.dim),
            )
            for m, s in zip(models, segs)
        ]
    except Exception as exc:
        return f"segment carry structure could not be traced: {exc!r}"
    if any(str(st) != str(structs[0]) for st in structs[1:]):
        return ("segment carry structures differ across models (the "
                "per-lane lax.switch needs identical carry avals)")
    return None


def select_lanes(mask, new, old):
    """Per-lane pytree select: ``new`` where ``mask`` else ``old``,
    broadcasting the (B,) mask over trailing leaf dims. Typed PRNG-key
    leaves route through key_data so any jax version selects them."""

    def leaf(n, o):
        try:
            is_key = jnp.issubdtype(n.dtype, jax.dtypes.prng_key)
        except Exception:
            is_key = False
        if is_key:
            nd, od = jax.random.key_data(n), jax.random.key_data(o)
            m = mask.reshape(mask.shape + (1,) * (nd.ndim - mask.ndim))
            return jax.random.wrap_key_data(jnp.where(m, nd, od))
        m = mask.reshape(mask.shape + (1,) * (n.ndim - mask.ndim))
        return jnp.where(m, n, o)

    return jax.tree.map(leaf, new, old)


def gather_lanes(tree, idx):
    """Row-gather every leaf of a lane pytree (leading axis = slots /
    lanes). Works on typed PRNG-key leaves too — key arrays support
    integer-array indexing."""
    return jax.tree.map(lambda x: x[idx], tree)


def occupancy(seg_steps, lane_sweeps):
    """Fraction of vector-lane segment slots that advanced a live
    candidate (``seg_steps`` productive steps out of ``B * sweeps``
    total lane slots). 1.0 = every lane busy every sweep; the shortfall
    is drain/imbalance idle time, while early-reject SAVINGS show up as
    fewer sweeps for the same resolved proposal count."""
    return np.where(lane_sweeps > 0,
                    seg_steps / np.maximum(lane_sweeps, 1), 1.0)
