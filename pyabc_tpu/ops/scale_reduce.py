"""Sharded scale reductions for adaptive distances (ISSUE 12 tentpole).

The adaptive-distance gate was the last reason the sharded multigen
kernel (``inference/util.py::_multigen_sharded``) refused the configs
PAPER.md says matter most: ``AdaptivePNormDistance.adaptive`` refits
per-statistic ``1/scale`` weights every generation over the record ring
of ALL evaluated simulations — and the sharded kernel keeps row
payloads strictly shard-local per generation. This module closes the
gap for every *moment-expressible* scale function:

1. each shard accumulates a fixed ``(MOMENT_ROWS, C)`` moment block
   IN-LOOP while its generation runs (sums, sums of squares, deviations
   from the observation, counts, extrema — a few scalars per
   statistic), so the ring's sum-stat rows are never kept live;
2. the per-generation collective is one all-gather of the stacked
   ``(n_shards, MOMENT_ROWS, C)`` partials — *scalar-per-stat columns*,
   riding the per-generation collective round the kernel already pays
   for the distance/weight columns (NO new host fetch: the SyncLedger
   count of an adaptive sharded run equals the non-adaptive run's);
3. a replicated combine + finisher every shard computes identically.

Why in-loop accumulation instead of reducing the record ring after the
generation: keeping the ring's ``(rec_cap, S)`` sum-stat rows live
changes how XLA materializes the lane's distance computation, and the
vmapped virtual-shard program then differs from the per-device
shard_map program at the ULP level — breaking the mesh == virtual-shard
bit-identity contract. Fixed-size in-loop accumulators leave the ring
rows dead and the lane program byte-stable (measured, not assumed:
tests/test_sharded.py pins the contract for adaptive configs).

Median-based scale functions (``median_absolute_deviation`` & friends)
and true two-pass functions (``mean_absolute_deviation``, which needs
deviations about the not-yet-known global mean) have no fixed-size
moment form: they stay on the replicated GSPMD fallback, and
``ABCSMC._sharded_incapable_reason`` names the functions a user can
switch to.
"""
from __future__ import annotations

#: rows of the per-shard moment block: sum, sum of squares, sum of
#: absolute deviations from the observation, count, max, min
MOMENT_ROWS = 6

#: scale-function names expressible over the moment block — importable
#: WITHOUT jax for gate checks and error messages
SHARDED_SCALE_NAMES = frozenset({
    "mean", "bias", "span", "standard_deviation",
    "root_mean_square_deviation",
    "mean_absolute_deviation_to_observation",
    "standard_deviation_to_observation",
})


def init_moments(C: int):
    """Zero moment block (extrema seeded at ∓inf so an empty shard
    contributes the identity under max/min merges)."""
    import jax.numpy as jnp

    z = jnp.zeros((C,), jnp.float32)
    return jnp.stack([
        z, z, z, z,
        jnp.full((C,), -jnp.inf, jnp.float32),
        jnp.full((C,), jnp.inf, jnp.float32),
    ])


def accumulate_moments(mom, cols, take, x0):
    """Fold one proposal round's record columns into the shard's moment
    block. ``cols (B, C)``, ``take`` = this round's ring-eligible rows
    (valid simulation AND inside the record window), ``x0 (C,)`` the
    observation in column space.

    ``take`` is either ``(B,)`` (every column of a taken row counts —
    the classic whole-row path) or ``(B, C)`` boolean (per-COLUMN
    eligibility: the segmented early-reject engine folds retired lanes'
    simulated PREFIX columns in, so each column's moments aggregate over
    every proposal that actually simulated it). The whole-row path keeps
    its scalar-count broadcast untouched — bool sums are exact in f32 at
    any realistic round count, so existing sharded-adaptive bit-identity
    is preserved."""
    import jax.numpy as jnp

    per_col = take.ndim == 2
    t = take if per_col else take[:, None]
    csum = jnp.where(t, cols, 0.0).sum(axis=0)
    csq = jnp.where(t, cols * cols, 0.0).sum(axis=0)
    cad = jnp.where(t, jnp.abs(cols - x0[None, :]), 0.0).sum(axis=0)
    if per_col:
        cnt = take.sum(axis=0).astype(jnp.float32)
    else:
        cnt = jnp.broadcast_to(
            take.sum().astype(jnp.float32), (cols.shape[1],)
        )
    cmax = jnp.where(t, cols, -jnp.inf).max(axis=0)
    cmin = jnp.where(t, cols, jnp.inf).min(axis=0)
    return jnp.stack([
        mom[0] + csum, mom[1] + csq, mom[2] + cad, mom[3] + cnt,
        jnp.maximum(mom[4], cmax), jnp.minimum(mom[5], cmin),
    ])


def combine_moments(parts):
    """Merge the stacked per-shard blocks ``(n_shards, MOMENT_ROWS, C)``
    into the global block — sums add, extrema reduce. Pure function of
    the stacked array in shard order, so the mesh all-gather and the
    virtual-shard identity produce bit-identical results."""
    import jax.numpy as jnp

    sums = parts[:, :4].sum(axis=0)
    mx = parts[:, 4].max(axis=0)
    mn = parts[:, 5].min(axis=0)
    return jnp.concatenate([sums, mx[None], mn[None]], axis=0)


def scale_from_moments(name: str):
    """The finisher ``fn(mom_global, x0) -> (C,)`` for a supported scale
    name, or None. Variance-bearing scales use the one-pass
    ``E[x²] - mean²`` form (clamped at 0) — a declared fp deviation from
    the unsharded two-pass device twin; the sharded contract compares
    mesh against virtual shards, which share this exact form."""
    if name not in SHARDED_SCALE_NAMES:
        return None
    import jax.numpy as jnp

    def _n(mom):
        return jnp.maximum(mom[3], 1.0)

    def _mean(mom):
        return mom[0] / _n(mom)

    def _var(mom):
        return jnp.maximum(mom[1] / _n(mom) - _mean(mom) ** 2, 0.0)

    impls = {
        "mean": lambda mom, x0: _mean(mom),
        "bias": lambda mom, x0: jnp.abs(_mean(mom) - x0),
        "span": lambda mom, x0: mom[4] - mom[5],
        "standard_deviation": lambda mom, x0: jnp.sqrt(_var(mom)),
        "root_mean_square_deviation": lambda mom, x0: jnp.sqrt(
            (_mean(mom) - x0) ** 2 + _var(mom)),
        "mean_absolute_deviation_to_observation":
            lambda mom, x0: mom[2] / _n(mom),
        "standard_deviation_to_observation": lambda mom, x0: jnp.sqrt(
            jnp.maximum(
                (mom[1] - 2.0 * x0 * mom[0] + mom[3] * x0 * x0)
                / _n(mom), 0.0)),
    }
    return impls[name]
