"""Device-side fetch compaction: pack accepted rows before the host fetch.

Round 5 measured the TPU tunnel at ~12 MB/s under concurrent streams with
a ~102 ms per-transfer latency floor; the fused loop's per-chunk fetch of
the full f32 reservoirs (m, theta, distance, log_weight, slot — 32 B per
accepted row at d=4, ~2 MB/chunk at pop 8192) made throughput INVERT with
population size (BASELINE.md round-5 notes). This module builds the
jit-able packing function that runs ON DEVICE right after the multigen
kernel, so only a dense, minimal payload crosses the tunnel:

- ``theta``, ``distance`` and ``log_weight`` are gathered into ONE
  contiguous ``(G, n_keep, d_max + 2)`` buffer in a narrowed dtype
  (float16 by default — see the precision audit in
  ``tests/test_fetch_precision.py`` and the dtype notes below); one buffer
  means one transfer instead of five per chunk, which matters as much as
  the bytes on a latency-floored link;
- rows are sliced to ``n_keep`` (the chunk's largest scheduled population)
  instead of the pow2-padded ring capacity;
- ``m`` ships as int8 only for multi-model runs (K = 1 reconstructs zeros
  host-side) and ``slot`` is elided entirely — the reservoir is written in
  slot order by construction (``_generation_while``'s compaction), so
  ``argsort(slot)`` is the identity and the host substitutes ``arange``;
- per-particle sum stats ship only for the generations History will
  persist (``History.wants_sum_stats``), cast to the same narrowed dtype.

Dtype notes (the documented precision audit): the packed values feed ONLY
the host-side History persist and component mirrors — the device carry
chain stays f32, so the inference trajectory (acceptances, epsilon trail,
refits) is bit-identical for every fetch dtype. float16 keeps a 10-bit
mantissa (~5e-4 relative), far inside History's round-trip tolerance for
posterior estimates (weighted mean/var parity asserted on the conjugate
Gaussian); bfloat16 (8-bit mantissa, ~4e-3) is offered for range-extreme
sum stats. Scalars (epsilons, pdf norms, model probabilities, adaptive
weights) always pass through untouched at f32.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

#: reservoir leaves replaced by the packed row buffer (or elided)
ROW_KEYS = ("theta", "distance", "log_weight", "m", "slot", "sumstats")

_DTYPES = {
    "float32": jnp.float32,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
}


def fetch_dtype_of(name: str):
    """Resolve a fetch-dtype name; raises on unsupported names so a typo
    fails at configuration time, not after a 20 s kernel compile."""
    try:
        return _DTYPES[str(name)]
    except KeyError:
        raise ValueError(
            f"unsupported fetch_dtype {name!r}: one of {sorted(_DTYPES)}"
        ) from None


def _cast_monotone_down(x, dtype):
    """Cast toward zero-ward ULPs: the result never EXCEEDS ``x`` in
    magnitude-signed order. Accepted distances carry the invariant
    ``d <= eps_used``; a round-to-nearest narrowing can push a stored
    distance half a ULP ABOVE the stored threshold (observed: 0.6001 vs
    eps 0.6 at f16), so the distance column rounds down instead —
    portable (multiply + cast only), error bounded by ~1.5 ULP."""
    if dtype == jnp.float32:
        return x.astype(dtype)
    step = 2.0 ** -10 if dtype == jnp.float16 else 2.0 ** -7
    down = x * jnp.where(x >= 0, 1.0 - step, 1.0 + step)
    cast = x.astype(dtype)
    over = cast.astype(x.dtype) > x
    return jnp.where(over, down.astype(dtype), cast)


def pack_outs(outs: dict, *, n_keep: int, dtype, keep_m: bool,
              ss_gens: tuple[int, ...] | str, m_dtype=jnp.int8,
              g_keep: int | None = None, merge_index=None) -> dict:
    """Traceable compaction of a multigen ``outs`` tree (leading G axis).

    Returns the fetch tree: every non-row leaf passes through (sliced to
    the chunk's ``g_keep`` ACTIVE generations — a short tail/drain chunk
    must not ship the scan's inactive garbage rows); the row leaves
    collapse into ``rows`` (+ optional ``m`` / ``__ss_rows__``).
    ``ss_gens`` is the static tuple of chunk-relative generations whose
    sum stats the host wants, or ``"all"`` (an empty tuple ships NO sum
    stats — the host reconstructs the empty map).

    ``merge_index`` (sharded fused sampling): a static ``(n_keep,)``
    gather over the row axis that merges the shard-blocked per-device
    reservoir layout (``ops/shard.py::merge_index``) into dense
    accepted-row order. Running it HERE — inside the one jitted fetch
    program — is what makes the cross-device row merge a
    chunk-boundary-only collective: GSPMD lowers the gather over the
    sharded axis into a single all-gather riding the fetch, adding zero
    blocking syncs.
    """
    if g_keep is not None:
        # every leaf of the scan's ys carries the leading G axis,
        # including structured distance params (dicts/tuples)
        outs = jax.tree.map(lambda v: v[:g_keep], outs)

    if merge_index is not None:
        idx = jnp.asarray(merge_index, jnp.int32)

        def take(v):
            return v[:, idx]
    else:
        def take(v):
            return v[:, :n_keep]

    packed = {k: v for k, v in outs.items() if k not in ROW_KEYS}
    packed["rows"] = jnp.concatenate(
        [
            take(outs["theta"]).astype(dtype),
            _cast_monotone_down(
                take(outs["distance"])[..., None], dtype),
            take(outs["log_weight"])[..., None].astype(dtype),
        ],
        axis=-1,
    )
    if keep_m:
        packed["m"] = take(outs["m"]).astype(m_dtype)
    if ss_gens == "all":
        packed["sumstats"] = take(outs["sumstats"]).astype(dtype)
    elif ss_gens:
        packed["__ss_rows__"] = {
            int(g): take(outs["sumstats"])[int(g)].astype(dtype)
            for g in ss_gens
        }
    return packed


def unpack_rows(rows, d_max: int):
    """Host-side split of the packed row buffer -> (theta, distance,
    log_weight), upcast to f32/f64 (History and the component mirrors
    compute in f64; the narrowing lives on the wire only)."""
    import numpy as np

    rows = np.asarray(rows)
    theta = rows[..., :d_max].astype(np.float32)
    distance = rows[..., d_max].astype(np.float64)
    log_weight = rows[..., d_max + 1].astype(np.float64)
    return theta, distance, log_weight
