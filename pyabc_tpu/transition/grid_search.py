"""Bandwidth / hyperparameter selection for transitions.

Reference parity: ``pyabc/transition/grid_search.py::GridSearchCV`` — the
reference subclasses sklearn's GridSearchCV to pick e.g. the MVN ``scaling``
by cross-validated KDE likelihood. Re-implemented directly (weighted K-fold
held-out log-likelihood) to avoid depending on sklearn internals.
"""
from __future__ import annotations

import copy
from itertools import product

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from .base import Transition
from .multivariatenormal import _LOG_2PI, MultivariateNormalTransition


def fold_ids(n_rows: int, cv: int, n_cap: int) -> np.ndarray:
    """THE fixed-seed fold-assignment rule, shared by the static
    device_fit path and the per-generation fold tables the fused
    ListPopulationSize loop ships (smc.py) — one implementation, so the
    two can never drift apart: ``arange(n_rows) % min(cv, n_rows)``
    shuffled by ``default_rng(0)``; rows beyond ``n_rows`` get -1 (no
    fold: always train with zero weight, never test)."""
    n_folds = min(int(cv), int(n_rows))
    out = np.full(int(n_cap), -1, np.int32)
    head = np.arange(int(n_rows)) % n_folds
    np.random.default_rng(0).shuffle(head)
    out[: int(n_rows)] = head
    return out


class GridSearchCV(Transition):
    """Pick the best hyperparameters by K-fold held-out log-likelihood.

    ``GridSearchCV(MultivariateNormalTransition(), {"scaling": [0.5, 1, 2]})``
    behaves as a Transition: fit() runs the search and fits the winner.
    """

    def __init__(self, estimator: Transition, param_grid: dict,
                 cv: int = 5):
        self.estimator = estimator
        self.param_grid = {k: list(v) for k, v in param_grid.items()}
        self.cv = int(cv)
        self.best_estimator_: Transition | None = None
        self.best_params_: dict | None = None
        self.best_score_: float | None = None

    def _candidates(self):
        keys = list(self.param_grid)
        for combo in product(*(self.param_grid[k] for k in keys)):
            yield dict(zip(keys, combo))

    def fit(self, X: pd.DataFrame, w: np.ndarray) -> None:
        self.store_fit_params(X, w)
        n = len(X)
        n_folds = min(self.cv, n)
        folds = fold_ids(n, self.cv, n)
        best_score, best_params = -np.inf, None
        for params in self._candidates():
            scores = []
            for f in range(n_folds):
                train, test = folds != f, folds == f
                if train.sum() < 2 or test.sum() < 1:
                    continue
                est = copy.deepcopy(self.estimator)
                for k, v in params.items():
                    setattr(est, k, v)
                try:
                    est.fit(X[train], np.asarray(w)[train])
                    dens = np.asarray(est.pdf(X[test]), np.float64)
                except Exception:
                    scores = []
                    break
                wt = np.asarray(w)[test]
                scores.append(
                    float(np.sum(wt * np.log(np.maximum(dens, 1e-300))))
                )
            score = np.sum(scores) if scores else -np.inf
            if score > best_score:
                best_score, best_params = score, params
        if best_params is None:
            best_params = next(self._candidates())
        self.best_params_ = best_params
        self.best_score_ = float(best_score)
        est = copy.deepcopy(self.estimator)
        for k, v in best_params.items():
            setattr(est, k, v)
        est.fit(X, w)
        self.best_estimator_ = est

    # delegate the Transition API to the fitted winner -----------------------
    def rvs_single(self):
        return self.best_estimator_.rvs_single()

    def rvs(self, size=None):
        return self.best_estimator_.rvs(size)

    def pdf(self, x):
        return self.best_estimator_.pdf(x)

    def is_device_compatible(self):
        # the class-level device fns below delegate to the MVN statics
        # (the reference's canonical GridSearchCV use); other estimators
        # run the host path
        return type(self.estimator) is MultivariateNormalTransition

    def device_params(self):
        return self.best_estimator_.device_params()

    device_rvs = staticmethod(MultivariateNormalTransition.device_rvs)
    device_logpdf = staticmethod(MultivariateNormalTransition.device_logpdf)

    @staticmethod
    def device_fit(thetas, weights, *, dim: int, scalings: tuple,
                   cv: int, bandwidth_selector, n: int | None = None,
                   folds=None):
        """Traceable twin of :meth:`fit` for the fused multi-generation
        run: IN-KERNEL cross-validated bandwidth selection.

        One scaling=1 MVN fit per fold is shared across all candidate
        scalings (scaling enters the covariance multiplicatively, so a
        candidate's held-out log-density is the fold fit's density with
        ``maha / s^2`` and ``logdet + 2 dim log s``); the winner scales
        the full-data fit the same way. Fold assignment replicates the
        host rule exactly — ``arange(n) % n_folds`` shuffled by the same
        fixed seed over the ACTUAL population size ``n`` (host-static
        under the ConstantPopulationSize fused gate) — and padding lanes
        beyond n belong to no fold: they stay in every train set with
        zero weight and are never test rows.
        """
        n_cap = thetas.shape[0]
        s_arr = jnp.asarray(scalings, jnp.float32)
        log_s = jnp.log(s_arr)
        scores = jnp.zeros(len(scalings), jnp.float32)
        if folds is None:
            n_rows = n_cap if n is None else min(int(n), n_cap)
            folds_np = fold_ids(n_rows, int(cv), n_cap)
            n_folds = min(int(cv), n_rows)
            folds_arr = jnp.asarray(folds_np)
        else:
            # per-generation DYNAMIC fold assignment (ListPopulationSize
            # fused runs): membership arrives as a traced (n_cap,) array
            # built by the host with the same fixed-seed rule per that
            # generation's n, so test rows are masked, not gathered
            n_folds = int(cv)
            folds_np = None
            folds_arr = folds
        for f in range(n_folds):
            train_w = jnp.where(folds_arr != f, weights, 0.0)
            fit_f = MultivariateNormalTransition.device_fit(
                thetas, train_w, dim=dim, scaling=1.0,
                bandwidth_selector=bandwidth_selector,
            )
            if folds_np is not None:
                # fold membership is host-side static: gather the test
                # rows so the per-fold scoring costs ~1/cv of the full
                # maha matrix
                test_idx = np.where(folds_np == f)[0]
                q = thetas[test_idx]
                qw = weights[test_idx]
            else:
                q = thetas
                qw = jnp.where(folds_arr == f, weights, 0.0)
            # host parity (grid_search.py fit): a fold whose train split
            # holds < 2 of this model's rows, or whose test split holds
            # none, is SKIPPED — critical for per-model masked weights in
            # multimodel fused runs, where a small model's rows may all
            # land in one row-indexed fold and the zero-weight fit would
            # otherwise score garbage (and for dynamic fold tables where
            # a small generation uses fewer than cv fold ids)
            fold_ok = ((train_w > 0).sum() >= 2) & ((qw > 0).sum() >= 1)
            diff = q[:, None, :] - fit_f["thetas"][None, :, :]
            maha = jnp.einsum("qnd,de,qne->qn", diff, fit_f["prec"], diff)
            for i in range(len(scalings)):
                s2 = jnp.exp(2.0 * log_s[i])
                log_comp = -0.5 * (
                    dim * _LOG_2PI + fit_f["logdet"]
                    + 2.0 * dim * log_s[i] + maha / s2
                )
                logdens = jax.scipy.special.logsumexp(
                    log_comp, b=fit_f["weights"][None, :], axis=1
                )
                logdens = jnp.maximum(logdens, np.log(1e-300))
                scores = scores.at[i].add(
                    jnp.where(fold_ok, jnp.sum(qw * logdens), 0.0)
                )
        s_best = s_arr[jnp.argmax(scores)]
        full = MultivariateNormalTransition.device_fit(
            thetas, weights, dim=dim, scaling=1.0,
            bandwidth_selector=bandwidth_selector,
        )
        return {
            "thetas": full["thetas"],
            "weights": full["weights"],
            "chol": full["chol"] * s_best,
            "prec": full["prec"] / (s_best * s_best),
            "center": full["center"],
            "thetas_c": full["thetas_c"],
            # quad scales with prec (see MVN device_fit's cached v^T P v)
            "quad": full["quad"] / (s_best * s_best),
            "logdet": full["logdet"] + 2.0 * dim * jnp.log(s_best),
            "dim": full["dim"],
        }

    def __repr__(self):
        return f"GridSearchCV({self.estimator!r}, {self.param_grid})"
