"""Bandwidth / hyperparameter selection for transitions.

Reference parity: ``pyabc/transition/grid_search.py::GridSearchCV`` — the
reference subclasses sklearn's GridSearchCV to pick e.g. the MVN ``scaling``
by cross-validated KDE likelihood. Re-implemented directly (weighted K-fold
held-out log-likelihood) to avoid depending on sklearn internals.
"""
from __future__ import annotations

import copy
from itertools import product

import numpy as np
import pandas as pd

from .base import Transition


class GridSearchCV(Transition):
    """Pick the best hyperparameters by K-fold held-out log-likelihood.

    ``GridSearchCV(MultivariateNormalTransition(), {"scaling": [0.5, 1, 2]})``
    behaves as a Transition: fit() runs the search and fits the winner.
    """

    def __init__(self, estimator: Transition, param_grid: dict,
                 cv: int = 5):
        self.estimator = estimator
        self.param_grid = {k: list(v) for k, v in param_grid.items()}
        self.cv = int(cv)
        self.best_estimator_: Transition | None = None
        self.best_params_: dict | None = None
        self.best_score_: float | None = None

    def _candidates(self):
        keys = list(self.param_grid)
        for combo in product(*(self.param_grid[k] for k in keys)):
            yield dict(zip(keys, combo))

    def fit(self, X: pd.DataFrame, w: np.ndarray) -> None:
        self.store_fit_params(X, w)
        n = len(X)
        n_folds = min(self.cv, n)
        folds = np.arange(n) % n_folds
        rng = np.random.default_rng(0)
        rng.shuffle(folds)
        best_score, best_params = -np.inf, None
        for params in self._candidates():
            scores = []
            for f in range(n_folds):
                train, test = folds != f, folds == f
                if train.sum() < 2 or test.sum() < 1:
                    continue
                est = copy.deepcopy(self.estimator)
                for k, v in params.items():
                    setattr(est, k, v)
                try:
                    est.fit(X[train], np.asarray(w)[train])
                    dens = np.asarray(est.pdf(X[test]), np.float64)
                except Exception:
                    scores = []
                    break
                wt = np.asarray(w)[test]
                scores.append(
                    float(np.sum(wt * np.log(np.maximum(dens, 1e-300))))
                )
            score = np.sum(scores) if scores else -np.inf
            if score > best_score:
                best_score, best_params = score, params
        if best_params is None:
            best_params = next(self._candidates())
        self.best_params_ = best_params
        self.best_score_ = float(best_score)
        est = copy.deepcopy(self.estimator)
        for k, v in best_params.items():
            setattr(est, k, v)
        est.fit(X, w)
        self.best_estimator_ = est

    # delegate the Transition API to the fitted winner -----------------------
    def rvs_single(self):
        return self.best_estimator_.rvs_single()

    def rvs(self, size=None):
        return self.best_estimator_.rvs(size)

    def pdf(self, x):
        return self.best_estimator_.pdf(x)

    def is_device_compatible(self):
        return (self.best_estimator_ is not None
                and self.best_estimator_.is_device_compatible())

    def device_params(self):
        return self.best_estimator_.device_params()

    @property
    def device_rvs(self):
        return type(self.best_estimator_).device_rvs

    @property
    def device_logpdf(self):
        return type(self.best_estimator_).device_logpdf

    def __repr__(self):
        return f"GridSearchCV({self.estimator!r}, {self.param_grid})"
