from .aggregated import AggregatedTransition
from .base import DiscreteTransition, Transition
from .exceptions import NotEnoughParticles
from .grid_search import GridSearchCV
from .local_transition import LocalTransition
from .model_perturbation import ModelPerturbationKernel
from .multivariatenormal import MultivariateNormalTransition
from .randomwalk import (
    DiscreteJumpTransition,
    DiscreteRandomWalkTransition,
    PerturbationKernel,
)
from .util import scott_rule_of_thumb, silverman_rule_of_thumb, smart_cov

__all__ = [
    "Transition", "DiscreteTransition", "NotEnoughParticles",
    "MultivariateNormalTransition", "LocalTransition",
    "DiscreteRandomWalkTransition", "DiscreteJumpTransition",
    "PerturbationKernel", "AggregatedTransition", "GridSearchCV",
    "ModelPerturbationKernel",
    "smart_cov", "scott_rule_of_thumb", "silverman_rule_of_thumb",
]
