"""Per-parameter-subset composite transition.

Reference parity: ``pyabc/transition/base.py::AggregatedTransition`` (newer
versions) — maps disjoint parameter subsets to sub-transitions (e.g. discrete
jump kernel for an integer parameter, MVN for the continuous rest); density is
the product, sampling is independent per subset.
"""
from __future__ import annotations

from typing import Mapping

import numpy as np
import pandas as pd

from .base import Transition


class AggregatedTransition(Transition):
    def __init__(self, mapping: Mapping):
        """``mapping``: {param_name_or_tuple: Transition}."""
        self.mapping = {
            (k if isinstance(k, tuple) else (k,)): v for k, v in mapping.items()
        }

    def fit(self, X: pd.DataFrame, w: np.ndarray) -> None:
        self.store_fit_params(X, w)
        for keys, trans in self.mapping.items():
            trans.fit(X[list(keys)], w)

    def rvs_single(self) -> pd.Series:
        parts = [trans.rvs_single() for trans in self.mapping.values()]
        combined = pd.concat(parts)
        return combined[self.X.columns] if self.X is not None else combined

    def pdf(self, x: pd.Series | pd.DataFrame):
        vals = None
        for keys, trans in self.mapping.items():
            if isinstance(x, pd.DataFrame):
                sub = x[list(keys)]
            else:
                sub = x[list(keys)]
            p = np.asarray(trans.pdf(sub), np.float64)
            vals = p if vals is None else vals * p
        if np.ndim(vals) == 0 or (hasattr(vals, "shape") and vals.shape == ()):
            return float(vals)
        return vals
