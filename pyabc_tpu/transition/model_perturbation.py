"""Model-index perturbation kernel for multi-model inference.

Reference parity: ``pyabc/random_variables.py::ModelPerturbationKernel``
(location varies by version; semantics identical): with probability
``probability_to_stay`` keep the ancestor's model index, otherwise jump
uniformly to one of the other models.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class ModelPerturbationKernel:
    def __init__(self, nr_of_models: int, probability_to_stay: float | None = None):
        self.nr_of_models = int(nr_of_models)
        if probability_to_stay is None:
            self.probability_to_stay = 1.0 if nr_of_models == 1 else 0.7
        else:
            self.probability_to_stay = float(np.clip(probability_to_stay, 0, 1))

    def _transition_matrix(self) -> np.ndarray:
        """P[m, m'] = pmf of proposing m' from ancestor m."""
        K = self.nr_of_models
        if K == 1:
            return np.ones((1, 1))
        stay = self.probability_to_stay
        off = (1.0 - stay) / (K - 1)
        P = np.full((K, K), off)
        np.fill_diagonal(P, stay)
        return P

    def rvs(self, m: int) -> int:
        if not 0 <= m < self.nr_of_models:
            raise ValueError(f"model index {m} out of range")
        return int(np.random.choice(self.nr_of_models,
                                    p=self._transition_matrix()[m]))

    def pmf(self, n: int, m: int) -> float:
        """Probability of proposing n given ancestor m."""
        if not (0 <= n < self.nr_of_models and 0 <= m < self.nr_of_models):
            raise ValueError("model index out of range")
        return float(self._transition_matrix()[m, n])

    # ------------------------------------------------------------- device
    def device_params(self):
        # numpy, not jnp: this feeds host-side dyn-arg assembly every
        # generation; a device array here costs a TPU round-trip per call
        return np.asarray(self._transition_matrix(), np.float32)

    @staticmethod
    def device_rvs(key, m, matrix):
        """Traceable: propose a model index from ancestor index m."""
        return jax.random.choice(key, matrix.shape[0], p=matrix[m])

    @staticmethod
    def device_logpmf(n, m, matrix):
        return jnp.log(matrix[m, n])

    def __repr__(self):
        return (f"ModelPerturbationKernel(nr_of_models={self.nr_of_models}, "
                f"probability_to_stay={self.probability_to_stay})")
