"""Transition (perturbation kernel) base.

Reference parity: ``pyabc/transition/base.py::{Transition, DiscreteTransition}``
— sklearn-estimator-like: ``fit(X: DataFrame, w)``, ``rvs()/rvs_single()``,
``pdf(x)``, plus ``mean_cv``/``required_nr_samples`` used by the adaptive
population-size machinery.

TPU-first contract: a fitted transition additionally exposes
``device_params() -> pytree of jnp arrays`` and the class exposes traceable
``device_rvs(key, params) -> theta`` / ``device_logpdf(theta, params)`` used
inside the jitted generation kernel — proposal sampling and the KDE mixture
density for importance weights both run fully batched on device.
"""
from __future__ import annotations

from abc import ABC, abstractmethod

import numpy as np
import pandas as pd

from ..core.weighted_statistics import effective_sample_size
from .exceptions import NotEnoughParticles


class Transition(ABC):
    """Abstract perturbation kernel."""

    NR_BOOTSTRAP = 5
    X: pd.DataFrame | None = None
    w: np.ndarray | None = None

    @abstractmethod
    def fit(self, X: pd.DataFrame, w: np.ndarray) -> None:
        """Fit to weighted particles (weights sum to 1)."""

    @abstractmethod
    def rvs_single(self) -> pd.Series:
        """Draw one perturbed parameter."""

    def rvs(self, size: int | None = None) -> pd.Series | pd.DataFrame:
        if size is None:
            return self.rvs_single()
        return pd.DataFrame([self.rvs_single() for _ in range(size)])

    @abstractmethod
    def pdf(self, x: pd.Series | pd.DataFrame):
        """Density of the fitted kernel at x."""

    def store_fit_params(self, X: pd.DataFrame, w: np.ndarray) -> None:
        if len(X) == 0:
            raise NotEnoughParticles("fitting to no samples")
        if len(X) != len(w):
            raise ValueError("X and w must have equal length")
        total = np.sum(w)
        if not np.isclose(total, 1.0):
            w = np.asarray(w, np.float64) / total
        self.X = X
        self.w = np.asarray(w, np.float64)

    # ------------------------------------------------- adaptive pop size
    def mean_cv(self, n_samples: int | None = None) -> float:
        """Bootstrap coefficient of variation of the KDE under resampling
        (reference ``Transition.mean_cv``, used by AdaptivePopulationSize)."""
        if self.X is None:
            raise NotEnoughParticles("transition not fitted")
        if n_samples is None:
            n_samples = len(self.X)
        n_samples = max(int(n_samples), 2)
        rng = np.random.default_rng(0)
        test_points = self.X
        densities = []
        for _ in range(self.NR_BOOTSTRAP):
            idx = rng.choice(len(self.X), size=n_samples, p=self.w)
            boot_X = self.X.iloc[idx]
            boot_w = np.ones(n_samples) / n_samples
            cp = self.copy_unfitted()
            cp.fit(boot_X, boot_w)
            densities.append(np.asarray(cp.pdf(test_points), np.float64))
        densities = np.stack(densities)
        mean = densities.mean(axis=0)
        std = densities.std(axis=0)
        with np.errstate(invalid="ignore", divide="ignore"):
            cvs = np.where(mean > 0, std / mean, 0.0)
        return float(np.average(cvs, weights=self.w))

    def required_nr_samples(self, coefficient_of_variation: float) -> int:
        """Smallest n with bootstrap CV below the target (bisection;
        reference ``Transition.required_nr_samples``)."""
        if self.X is None:
            raise NotEnoughParticles("transition not fitted")
        lo, hi = 10, max(10 * len(self.X), 1000)
        if self.mean_cv(hi) > coefficient_of_variation:
            return hi
        while lo < hi:
            mid = (lo + hi) // 2
            if self.mean_cv(mid) <= coefficient_of_variation:
                hi = mid
            else:
                lo = mid + 1
        return int(hi)

    def copy_unfitted(self) -> "Transition":
        """A fresh instance with the same hyperparameters."""
        import copy

        cp = copy.copy(self)
        cp.X = None
        cp.w = None
        return cp

    def ess(self) -> float:
        return effective_sample_size(self.w) if self.w is not None else 0.0

    # ------------------------------------------------------------- device
    def is_device_compatible(self) -> bool:
        return False

    def device_params(self):
        """Pytree of jnp arrays describing the fitted kernel."""
        raise NotImplementedError

    @staticmethod
    def device_rvs(key, params):
        """Traceable: one proposal draw from the fitted kernel."""
        raise NotImplementedError

    @staticmethod
    def device_logpdf(theta, params):
        """Traceable: log density of the fitted kernel at theta."""
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}()"


class DiscreteTransition(Transition):
    """Base for transitions over discrete parameters (pyabc DiscreteTransition)."""
