"""Locally-adaptive Gaussian perturbation kernel.

Reference parity: ``pyabc/transition/local_transition.py::LocalTransition`` —
each ancestor particle gets its own covariance estimated from its k nearest
neighbors (reference uses a scipy KDTree + per-particle Silverman scaling).

TPU-first shift: neighbor search is a dense pairwise-distance + ``top_k``
(O(n^2), MXU-friendly, fine to n ~ 1e4 — SURVEY.md §7.3.4), and the fitted
kernel is stored as per-particle Cholesky factors ``(n, d, d)`` so device
sampling/pdf are fully batched.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from ..core.random_choice import fast_random_choice
from .base import Transition
from .exceptions import NotEnoughParticles
from .util import silverman_rule_of_thumb

_LOG_2PI = math.log(2.0 * math.pi)


class LocalTransition(Transition):
    """k-nearest-neighbor local-covariance Gaussian KDE (pyabc LocalTransition).

    ``k`` neighbors per particle (default ``k_fraction * n``, at least
    dim + 1); ``scaling`` multiplies the per-particle covariance.
    """

    EPS = 1e-3

    @staticmethod
    def device_refit_min_count(dim: int) -> int:
        """Minimum accepted particles for an in-kernel refit to be valid —
        below it the host :meth:`fit` raises NotEnoughParticles and the
        orchestrator reuses the previous generation's fit; the multigen
        kernel mirrors that by carrying the old params forward."""
        return dim + 1

    def __init__(self, k: int | None = None, k_fraction: float = 0.25,
                 scaling: float = 1.0, k_max: int | None = None,
                 selection: str = "auto"):
        self.k = k
        self.k_fraction = float(k_fraction)
        self.scaling = float(scaling)
        #: optional cap on the effective neighbor count — a DECLARED
        #: deviation from the reference's pure ``k_fraction * n`` rule for
        #: very large populations, where k grows into the thousands and
        #: the extra neighbors stop changing the local covariance while
        #: still paying O(n * k) per refit. None keeps exact parity.
        self.k_max = int(k_max) if k_max is not None else None
        #: in-kernel neighbor selection: "topk" (exact sort), "threshold"
        #: (radius bisection + masked gather, ops/select.py), or "auto"
        #: (threshold above ops.select.DEFAULT_TOPK_CUTOFF)
        if selection not in ("auto", "topk", "threshold"):
            raise ValueError(
                f"selection must be auto/topk/threshold, got {selection!r}"
            )
        self.selection = str(selection)
        self._chols: np.ndarray | None = None
        self._precs: np.ndarray | None = None
        self._logdets: np.ndarray | None = None

    def _effective_k(self, n: int, dim: int) -> int:
        if self.k is not None:
            k = self.k
        else:
            k = int(round(self.k_fraction * n))
        if self.k_max is not None:
            k = min(k, self.k_max)
        return int(np.clip(k, dim + 1, n))

    def fit(self, X: pd.DataFrame, w: np.ndarray) -> None:
        # validate BEFORE store_fit_params: self.X non-None is the fitted
        # indicator downstream (device_params / rvs_single); storing first and
        # then raising would leave a half-fitted object that crashes later
        arr = np.asarray(X, np.float64)
        n, dim = arr.shape
        if n < dim + 1:
            raise NotEnoughParticles(
                f"LocalTransition needs > dim+1={dim + 1} particles, got {n}"
            )
        self.store_fit_params(X, w)
        k = self._effective_k(n, dim)
        # dense pairwise sq-distances; top-k smallest per row
        sq = ((arr[:, None, :] - arr[None, :, :]) ** 2).sum(-1)
        nn_idx = np.argpartition(sq, kth=k - 1, axis=1)[:, :k]  # (n, k)
        factor = silverman_rule_of_thumb(k, dim) * self.scaling
        covs = np.empty((n, dim, dim))
        for i in range(n):
            neigh = arr[nn_idx[i]]
            centered = neigh - arr[i]
            cov = centered.T @ centered / k
            cov = cov * factor**2
            # regularize: relative jitter on the diagonal (reference EPS role)
            tr = np.trace(cov) / dim
            cov += np.eye(dim) * max(tr, 1e-10) * self.EPS
            covs[i] = cov
        self._chols = np.linalg.cholesky(covs)
        self._precs = np.linalg.inv(covs)
        sign, logdets = np.linalg.slogdet(covs)
        self._logdets = logdets

    def rvs_single(self) -> pd.Series:
        idx = fast_random_choice(self.w)
        theta = np.asarray(self.X.iloc[idx], np.float64)
        perturbed = theta + self._chols[idx] @ np.random.normal(size=len(theta))
        return pd.Series(perturbed, index=self.X.columns)

    def pdf(self, x: pd.Series | pd.DataFrame):
        arr = np.asarray(x, np.float64)
        single = arr.ndim == 1
        arr = np.atleast_2d(arr)
        thetas = np.asarray(self.X, np.float64)
        dim = thetas.shape[1]
        diff = arr[:, None, :] - thetas[None, :, :]  # (q, n, d)
        maha = np.einsum("qnd,nde,qne->qn", diff, self._precs, diff)
        log_comp = -0.5 * (dim * _LOG_2PI + self._logdets[None, :] + maha)
        dens = np.exp(log_comp) @ self.w
        return float(dens[0]) if single else dens

    # ------------------------------------------------------------- device
    def is_device_compatible(self) -> bool:
        return True

    def device_params(self):
        return {
            "thetas": np.asarray(self.X, np.float32),
            "weights": np.asarray(self.w, np.float32),
            "chols": np.asarray(self._chols, np.float32),
            "precs": np.asarray(self._precs, np.float32),
            "logdets": np.asarray(self._logdets, np.float32),
            # true dim; see MultivariateNormalTransition.device_params
            "dim": np.float32(self.X.shape[1]),
        }

    @staticmethod
    def _device_cov_field(thetas, weights, *, dim: int, scaling: float,
                          k: int | None = None, k_cap: int | None = None,
                          k_fixed: int = -1, k_fraction: float = 0.25,
                          k_max: int | None = None,
                          block_rows: int | None = None,
                          selection: str = "auto",
                          topk_cutoff: int | None = None,
                          bisect_stride: int | None = None):
        """The selection + covariance half of :meth:`device_fit`: per-row
        jittered covariances (padded dims unit-diagonal, ready for
        factorization) from either exact ``top_k`` neighbor sets or the
        threshold (radius-bisection) selection of ``ops/select.py``.

        Returns ``(covs, X, w, vmask, outer)``. See :meth:`device_fit`
        for the contract; the split exists so the incremental refit
        (:meth:`device_fit_update`) can reuse the covariance field and
        factorize only changed rows.

        ``thetas (n_cap, d_max)`` zero-padded accepted particles,
        ``weights (n_cap,)`` normalized with zeros on empty slots. Neighbor
        search is the same pairwise-distance + ``top_k`` as the host path
        (invalid slots are excluded as neighbor CANDIDATES via an inf
        distance; their own rows get finite jittered covariances but carry
        zero weight, so they are never resampled and contribute nothing to
        the mixture pdf).

        The neighbor count follows the host ``_effective_k`` rule ON
        DEVICE from the valid-row count c — ``clip(k_fixed or
        round(k_fraction*c), dim+1, c)`` — so per-model masked refits
        (multimodel fused chunks, where each model's accepted count varies
        by generation) match the host's per-model k. ``k_cap`` is the
        static top_k bound (the rule's value at the full population);
        ``k`` forces a fixed static count (back-compat shorthand for
        k_cap=k_fixed=k).

        SURVEY.md §7.3.4 scale note: beyond n ~ 1e4 the dense
        (n, n, d) difference tensor is the first thing to fall off a
        v5e's HBM, so large populations TILE the neighbor search over
        row blocks (``block_rows``, auto-chosen: dense to 4096, 2048-row
        tiles above). Each tile computes its (block, n) squared
        distances via the MXU decomposition |x|^2 - 2 x.y + |y|^2 —
        peak memory O(block * n) instead of O(n^2 * d) — and only the
        (n, k_cap) neighbor indices are kept.
        """
        from ..ops import select as sel_ops

        n_cap, d_max = thetas.shape
        if k is not None:
            k_cap, k_fixed = int(k), int(k)
        vmask = (jnp.arange(d_max) < dim).astype(thetas.dtype)
        outer = vmask[:, None] * vmask[None, :]
        w = weights / jnp.maximum(weights.sum(), 1e-38)
        valid = weights > 0
        c = valid.sum()
        # EXACT host-rule parity for every possible count: the c -> k map
        # is precomputed in f64 numpy at trace time (an f32 product like
        # 0.1 * 25 = 2.5000002 would round differently than the host's
        # float64 round-half-even at representation boundaries) and
        # embedded as an HLO literal
        counts = np.arange(n_cap + 1)
        base = (np.full(n_cap + 1, k_fixed) if k_fixed > 0
                else np.round(k_fraction * counts))
        if k_max is not None:
            base = np.minimum(base, k_max)
        k_table = np.clip(
            base, dim + 1, np.maximum(counts, dim + 1)
        ).astype(np.int32)
        k_dyn = jnp.minimum(jnp.asarray(k_table)[c], k_cap)
        X = thetas * vmask[None, :]
        factor = silverman_rule_of_thumb(
            k_dyn.astype(thetas.dtype), dim
        ) * scaling
        cutoff = (sel_ops.DEFAULT_TOPK_CUTOFF if topk_cutoff is None
                  else int(topk_cutoff))
        if selection == "auto":
            selection = "threshold" if k_cap >= cutoff else "topk"
        stride = (sel_ops.default_stride(n_cap) if bisect_stride is None
                  else int(bisect_stride))

        def _covs_from_idx(rows_X, nn_idx_t, cnt_t):
            """Per-row jittered covariances for a block of rows given
            their neighbor indices (into the FULL X) and the per-row
            used neighbor count ``cnt_t``."""
            # dynamic-count mask: positions beyond the row's count and
            # invalid candidates (possible when a model's count is below
            # k_cap) contribute nothing; the buffer width is k_cap for
            # top_k and ceil(k_cap / stride) for threshold selection
            pos_ok = (jnp.arange(nn_idx_t.shape[1])[None, :]
                      < cnt_t[:, None]) & valid[nn_idx_t]
            neigh = X[nn_idx_t]  # (rows, k_cap, d_max)
            centered = (neigh - rows_X[:, None, :]) * pos_ok[..., None]
            cov = jnp.einsum("nkd,nke->nde", centered, centered) \
                / jnp.maximum(cnt_t, 1)[:, None, None]
            cov = cov * factor**2
            # host regularization: relative jitter on the REAL diagonal;
            # padded dims get a unit diagonal so the factorization is
            # well-posed (they are zeroed out of the outputs, like
            # pad_transition_params)
            tr = jnp.trace(cov, axis1=1, axis2=2) / dim
            jit = jnp.maximum(tr, 1e-10) * LocalTransition.EPS
            diag_add = jit[:, None] * vmask[None, :] + (1.0 - vmask)[None, :]
            return cov * outer[None] + jax.vmap(jnp.diag)(diag_add)

        def _select_tile(sqt, rows_X):
            """Neighbor selection on one (rows, n) distance tile ->
            jittered covariances. "topk": exact sort (host parity).
            "threshold": radius bisection + masked gather — no sort; the
            estimator divides by the REALIZED within-radius count."""
            if selection == "threshold":
                # the realized count can deviate from k_dyn by the
                # documented few percent (radius resolution, candidate
                # stride); the estimator divides by the realized count,
                # and the diagonal jitter keeps even a degenerate row
                # factorizable
                idx_t, cnt_t, _r = sel_ops.threshold_neighbors(
                    sqt, k_dyn, k_cap, stride=stride,
                )
                return _covs_from_idx(rows_X, idx_t, cnt_t)
            idx_t = jax.lax.top_k(-sqt, k_cap)[1]
            cnt_t = jnp.broadcast_to(k_dyn, (sqt.shape[0],))
            return _covs_from_idx(rows_X, idx_t, cnt_t)

        if block_rows is None:
            if n_cap <= 4096:
                block_rows = n_cap
            else:
                # largest divisor of n_cap <= 2048 (pow2 n_cap from the
                # fused loop hits 2048 exactly); an awkward n_cap with no
                # decent divisor falls back to the dense path rather than
                # rejecting a shape the old contract accepted
                block_rows = next(
                    (b for b in range(2048, 0, -1) if n_cap % b == 0), 1
                )
                if block_rows < 256:
                    block_rows = n_cap
        block_rows = min(block_rows, n_cap)
        if block_rows >= n_cap:
            diff = X[:, None, :] - X[None, :, :]
            sq = (diff * diff).sum(-1)
            sq = jnp.where(valid[None, :], sq, jnp.inf)
            covs = _select_tile(sq, X)
        else:
            if n_cap % block_rows:
                raise ValueError(
                    f"block_rows={block_rows} must divide n_cap={n_cap}"
                )
            norms = (X * X).sum(-1)

            def _tile(args):
                Xt, nt = args  # (block, d), (block,)
                sqt = nt[:, None] + norms[None, :] - 2.0 * (Xt @ X.T)
                # the decomposition can go slightly negative for
                # near-duplicate points; clamping keeps self-distance 0
                sqt = jnp.maximum(sqt, 0.0)
                sqt = jnp.where(valid[None, :], sqt, jnp.inf)
                return _select_tile(sqt, Xt)

            covs = jax.lax.map(
                _tile,
                (X.reshape(-1, block_rows, d_max),
                 norms.reshape(-1, block_rows)),
            ).reshape(n_cap, d_max, d_max)
        return covs, X, w, vmask, outer

    @staticmethod
    def _device_factorize(cov, vmask, outer):
        """(chols, precs, logdets) from a batch of jittered covariances —
        the factorization half of the refit, split out so the incremental
        path can run it on changed rows only. Rows whose factorization
        fails escalate through the relative-jitter ladder
        (``transition.util.device_chol_guarded_batched``) instead of
        keeping silent NaN factors; a row still non-finite past the
        ladder is surfaced by the health word's psd_fail bit."""
        from .util import device_chol_guarded_batched

        chols, cov, _bad = device_chol_guarded_batched(cov)
        precs = jnp.linalg.inv(cov) * outer[None]
        logdets = 2.0 * jnp.sum(
            vmask[None, :] * jnp.log(jnp.maximum(
                jnp.diagonal(chols, axis1=1, axis2=2), 1e-38)),
            axis=1,
        )
        return chols * outer[None], precs, logdets

    @staticmethod
    def device_fit(thetas, weights, *, dim: int, scaling: float,
                   k: int | None = None, k_cap: int | None = None,
                   k_fixed: int = -1, k_fraction: float = 0.25,
                   k_max: int | None = None,
                   block_rows: int | None = None,
                   selection: str = "auto",
                   topk_cutoff: int | None = None,
                   bisect_stride: int | None = None):
        """Traceable twin of :meth:`fit` for the fused multi-generation
        run — full documentation on :meth:`_device_cov_field` (selection
        + covariances) and :meth:`_device_factorize`."""
        covs, X, w, vmask, outer = LocalTransition._device_cov_field(
            thetas, weights, dim=dim, scaling=scaling, k=k, k_cap=k_cap,
            k_fixed=k_fixed, k_fraction=k_fraction, k_max=k_max,
            block_rows=block_rows, selection=selection,
            topk_cutoff=topk_cutoff, bisect_stride=bisect_stride,
        )
        chols, precs, logdets = LocalTransition._device_factorize(
            covs, vmask, outer
        )
        return {
            "thetas": X,
            "weights": w,
            "chols": chols,
            "precs": precs,
            "logdets": logdets,
            "dim": jnp.float32(dim),
        }

    #: incremental-refit row-reuse tolerance: a row whose recomputed
    #: covariance differs from the carried factors' cov by less than
    #: this (relative to the row's covariance scale) keeps its previous
    #: Cholesky/precision/logdet — well above the ~1e-7 f32
    #: reconstruction noise of chol @ chol.T, well below any change a
    #: moved particle or neighbor produces
    REUSE_RTOL = 1e-5

    @staticmethod
    def device_fit_update(thetas, weights, prev: dict, *, dim: int,
                          scaling: float, k: int | None = None,
                          k_cap: int | None = None, k_fixed: int = -1,
                          k_fraction: float = 0.25,
                          k_max: int | None = None,
                          block_rows: int | None = None,
                          selection: str = "auto",
                          topk_cutoff: int | None = None,
                          bisect_stride: int | None = None,
                          fact_block: int = 1024):
        """Incremental refit (tentpole #3): recompute the covariance
        field, then factorize ONLY rows whose covariance actually
        changed vs the carried ``prev`` params — the changed-row mask
        compares each new covariance against ``chol_prev @ chol_prev.T``
        (a strictly sharper criterion than comparing neighbor index
        sets: an unchanged neighbor set with unchanged thetas gives an
        identical covariance, and a changed set that lands on the same
        covariance needs no new factors either). Unchanged rows copy the
        previous factors; changed rows run through
        :func:`~pyabc_tpu.ops.select.apply_rowwise_blocked`, whose
        while-loop trip count is the runtime ``ceil(n_changed / block)``
        — a mostly-unchanged population refit pays O(changed), not O(n),
        in Cholesky/inverse work.

        Returns ``(params, n_changed)``.
        """
        covs, X, w, vmask, outer = LocalTransition._device_cov_field(
            thetas, weights, dim=dim, scaling=scaling, k=k, k_cap=k_cap,
            k_fixed=k_fixed, k_fraction=k_fraction, k_max=k_max,
            block_rows=block_rows, selection=selection,
            topk_cutoff=topk_cutoff, bisect_stride=bisect_stride,
        )
        prev_ch = prev["chols"]
        cov_old = jnp.einsum("nij,nkj->nik", prev_ch, prev_ch)
        # stored chols are outer-masked (padded dims zero), so compare on
        # the valid block only; scale by the row's real-diagonal trace
        diff = jnp.abs((covs - cov_old) * outer[None]).max(axis=(1, 2))
        scale = jnp.maximum(
            jnp.sum(jnp.diagonal(covs, axis1=1, axis2=2)
                    * vmask[None, :], axis=1) / dim,
            1e-30,
        )
        changed = diff > LocalTransition.REUSE_RTOL * scale

        from ..ops.select import apply_rowwise_blocked

        def _fact(cov_b):
            return LocalTransition._device_factorize(cov_b, vmask, outer)

        (chols, precs, logdets), n_changed = apply_rowwise_blocked(
            _fact, changed,
            (prev["chols"], prev["precs"], prev["logdets"]),
            covs, block=fact_block,
        )
        params = {
            "thetas": X,
            "weights": w,
            "chols": chols,
            "precs": precs,
            "logdets": logdets,
            "dim": jnp.float32(dim),
        }
        return params, n_changed

    @staticmethod
    def device_rvs(key, params):
        k1, k2 = jax.random.split(key)
        idx = jax.random.choice(
            k1, params["weights"].shape[0], p=params["weights"]
        )
        theta = params["thetas"][idx]
        noise = params["chols"][idx] @ jax.random.normal(k2, theta.shape)
        return theta + noise

    @staticmethod
    def device_logpdf(theta, params):
        # DELIBERATELY the diff form, not the mean-centered quadratic
        # expansion the shared-covariance MVN mixture uses: with
        # per-component LOCAL precisions the expansion terms scale as
        # (population spread / local bandwidth)^2 — e.g. a bimodal
        # population with modes +-500 and local k-NN bandwidth 0.05 puts
        # ~2.5e9 into each cached term while maha at a mode is O(1),
        # which catastrophically cancels in f32 (measured ~5e6 nats of
        # error). The diff keeps operands O(maha) per component, and at
        # these shapes XLA compiles the vmapped einsum just as fast.
        thetas = params["thetas"]
        diff = theta[None, :] - thetas  # (n, d); padded dims diff exactly 0
        maha = jnp.einsum("nd,nde,ne->n", diff, params["precs"], diff)
        log_comp = -0.5 * (params["dim"] * _LOG_2PI + params["logdets"] + maha)
        return jax.scipy.special.logsumexp(
            log_comp, b=params["weights"], axis=0
        )

    def __repr__(self):
        return f"LocalTransition(k={self.k}, scaling={self.scaling})"
