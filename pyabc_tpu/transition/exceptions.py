"""Reference parity: ``pyabc/transition/exceptions.py::NotEnoughParticles``."""


class NotEnoughParticles(Exception):
    pass
