"""Global Gaussian-KDE perturbation kernel.

Reference parity: ``pyabc/transition/multivariatenormal.py::
MultivariateNormalTransition`` — resample an ancestor from the weighted
previous population, perturb with a Gaussian whose covariance is the weighted
population covariance scaled by a bandwidth rule (Scott/Silverman); the pdf is
the Gaussian mixture over all ancestors.

Device form: params = (thetas (n,d), weights (n,), chol (d,d), prec (d,d),
logdet, dim); `device_rvs` does categorical-ancestor + chol@normal, and
`device_logpdf` a logsumexp mixture — both traceable, batched by the
generation kernel via vmap.
"""
from __future__ import annotations

import math
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from ..core.random_choice import fast_random_choice
from .base import Transition
from .util import scott_rule_of_thumb, silverman_rule_of_thumb, smart_cov

_LOG_2PI = math.log(2.0 * math.pi)


class MultivariateNormalTransition(Transition):
    """Weighted Gaussian KDE transition (the reference default kernel)."""

    def __init__(self, scaling: float = 1.0,
                 bandwidth_selector: Callable = silverman_rule_of_thumb):
        self.scaling = float(scaling)
        self.bandwidth_selector = bandwidth_selector
        self._cov: np.ndarray | None = None
        self._chol: np.ndarray | None = None
        self._prec: np.ndarray | None = None
        self._logdet: float | None = None

    def fit(self, X: pd.DataFrame, w: np.ndarray) -> None:
        self.store_fit_params(X, w)
        arr = np.asarray(X, np.float64)
        dim = arr.shape[1]
        base_cov = smart_cov(arr, self.w)
        ess = self.ess()
        factor = self.bandwidth_selector(ess, dim)
        cov = base_cov * (self.scaling * factor) ** 2
        # guard: cov must stay positive definite after scaling
        try:
            chol = np.linalg.cholesky(cov)
        except np.linalg.LinAlgError:
            cov = cov + np.eye(dim) * 1e-10
            chol = np.linalg.cholesky(cov)
        self._cov = cov
        self._chol = chol
        self._prec = np.linalg.inv(cov)
        self._logdet = float(np.linalg.slogdet(cov)[1])

    @property
    def cov(self) -> np.ndarray:
        return self._cov

    def rvs_single(self) -> pd.Series:
        idx = fast_random_choice(self.w)
        theta = np.asarray(self.X.iloc[idx], np.float64)
        perturbed = theta + self._chol @ np.random.normal(size=len(theta))
        return pd.Series(perturbed, index=self.X.columns)

    def rvs(self, size: int | None = None):
        if size is None:
            return self.rvs_single()
        idx = np.random.choice(len(self.X), p=self.w, size=size)
        thetas = np.asarray(self.X, np.float64)[idx]
        noise = np.random.normal(size=thetas.shape) @ self._chol.T
        return pd.DataFrame(thetas + noise, columns=self.X.columns)

    def pdf(self, x: pd.Series | pd.DataFrame):
        arr = np.asarray(x, np.float64)
        single = arr.ndim == 1
        arr = np.atleast_2d(arr)
        thetas = np.asarray(self.X, np.float64)
        dim = thetas.shape[1]
        diff = arr[:, None, :] - thetas[None, :, :]  # (q, n, d)
        maha = np.einsum("qnd,de,qne->qn", diff, self._prec, diff)
        log_comp = -0.5 * (dim * _LOG_2PI + self._logdet + maha)
        dens = np.exp(log_comp) @ self.w
        return float(dens[0]) if single else dens

    # ------------------------------------------------------------- device
    def is_device_compatible(self) -> bool:
        return True

    def device_params(self):
        th = np.asarray(self.X, np.float32)
        prec = np.asarray(self._prec, np.float32)
        center = (np.asarray(self.w, np.float64) @ np.asarray(
            self.X, np.float64)).astype(np.float32)
        th_c = th - center[None, :]
        return {
            "thetas": th,
            "weights": np.asarray(self.w, np.float32),
            "chol": np.asarray(self._chol, np.float32),
            "prec": prec,
            "center": center,
            "thetas_c": th_c,
            "quad": np.einsum("nd,de,ne->n", th_c, prec, th_c).astype(
                np.float32
            ),
            "logdet": np.asarray(self._logdet, np.float32),
            # true parameter dim: padded copies keep this so the density
            # normalization constant is not biased by padding (thetas may be
            # padded to d_max for multi-model batching)
            "dim": np.float32(self.X.shape[1]),
        }

    @staticmethod
    def device_rvs(key, params):
        k1, k2 = jax.random.split(key)
        idx = jax.random.choice(
            k1, params["weights"].shape[0], p=params["weights"]
        )
        theta = params["thetas"][idx]
        noise = params["chol"] @ jax.random.normal(k2, theta.shape)
        return theta + noise

    @staticmethod
    def device_fit(thetas, weights, *, dim: int, scaling: float,
                   bandwidth_selector: Callable):
        """Traceable twin of :meth:`fit` for the multi-generation device run.

        ``thetas (n_cap, d)`` zero-padded, ``weights (n_cap,)`` normalized
        with zeros on empty slots (they contribute nothing to the weighted
        moments and are never resampled). Mirrors the host math: smart_cov
        weighted covariance -> bandwidth factor on the effective sample size
        -> Cholesky/precision/logdet, with the same degenerate-diagonal and
        positive-definiteness guards (in traceable ``where`` form).
        """
        d_max = thetas.shape[1]
        # padded trailing dims (multi-model batching pads theta to d_max):
        # they carry zero variance and must contribute NOTHING — zeroed out
        # of chol/prec and excluded from logdet, exactly like the host's
        # pad_transition_params zero-padding
        vmask = (jnp.arange(d_max) < dim).astype(thetas.dtype)
        w = weights / jnp.maximum(weights.sum(), 1e-38)
        mean = w @ thetas
        centered = thetas - mean
        cov = (centered * w[:, None]).T @ centered
        # smart_cov degenerate guard: non-positive diagonal gets a small fill
        diag = jnp.diagonal(cov)
        fill = jnp.abs(mean) * 1e-4 + 1e-8
        cov = cov + jnp.diag(jnp.where(diag <= 0, fill - diag, 0.0))
        ess = 1.0 / jnp.maximum(jnp.sum(w * w), 1e-38)
        factor = bandwidth_selector(ess, dim)
        cov = cov * (scaling * factor) ** 2
        # jitter-escalation retry (transition.util.CHOL_JITTER_LADDER):
        # the host path's single 1e-10 retry, escalated — a covariance
        # that stays non-finite is surfaced via the health word's
        # psd_fail bit (ops/health.params_unhealthy), never silently
        # propagated as NaN factors
        from .util import device_chol_guarded

        chol, cov, _chol_bad = device_chol_guarded(cov)
        prec = jnp.linalg.inv(cov)
        # logdet over the REAL dims only (padded block is block-diagonal,
        # so the leading diag of chol equals the submatrix factorization)
        logdet = 2.0 * jnp.sum(vmask * jnp.log(jnp.maximum(
            jnp.diagonal(chol), 1e-38
        )))
        outer = vmask[:, None] * vmask[None, :]
        prec = prec * outer
        th = thetas * vmask[None, :]
        # center the expansion on the weighted mean so the expanded
        # Mahalanobis terms stay O(maha) — expanding around the origin
        # suffers catastrophic f32 cancellation when |mean| >> bandwidth
        center = mean * vmask
        th_c = th - center[None, :]
        return {
            "thetas": th,
            "weights": w,
            "chol": chol * outer,
            "prec": prec,
            "center": center,
            "thetas_c": th_c,
            # centered component quadratic c_j^T P c_j, precomputed so the
            # batched mixture density never materializes a (B, n, d) diff
            # tensor (see device_logpdf)
            "quad": jnp.einsum("nd,de,ne->n", th_c, prec, th_c),
            "logdet": logdet,
            "dim": jnp.float32(dim),
        }

    @staticmethod
    def device_logpdf(theta, params):
        # maha_j = (q-c_j)^T P (q-c_j) expanded around the population MEAN:
        # with u = q - mu and v_j = c_j - mu (cached),
        # maha_j = u^T P u - 2 v_j^T (P u) + v_j^T P v_j. The expanded form
        # keeps every term a matvec/dot, so a vmap over B query lanes
        # becomes (B,d)@(d,d) and (n,d)@(d,B) MXU matmuls instead of
        # materializing a (B, n, d) diff tensor (~100x slower at B=4096,
        # n=1024 — it dominated the whole generation). Centering keeps the
        # term magnitudes O(maha), avoiding the f32 cancellation an
        # origin-centered expansion hits when |mean| >> bandwidth.
        center = params.get("center")
        if center is None or "thetas_c" not in params:
            # params from an older fit without the cache: stable diff form
            diff = theta[None, :] - params["thetas"]
            maha = jnp.einsum("nd,de,ne->n", diff, params["prec"], diff)
        else:
            u = theta - center
            Pu = params["prec"] @ u  # (d,); padded dims zeroed in prec
            cross = params["thetas_c"] @ Pu  # (n,)
            maha = u @ Pu - 2.0 * cross + params["quad"]
        log_comp = -0.5 * (params["dim"] * _LOG_2PI + params["logdet"] + maha)
        return jax.scipy.special.logsumexp(
            log_comp, b=params["weights"], axis=0
        )

    @staticmethod
    def device_mean_cv(params, key, n, *, dim: int, scaling: float,
                       bandwidth_selector: Callable, n_bootstrap: int):
        """Traceable twin of :meth:`Transition.mean_cv` — see the generic
        ``transition.util.device_mean_cv`` (shared with LocalTransition
        for the K>1 / non-MVN fused adaptive-n paths)."""
        from .util import device_mean_cv as _generic

        return _generic(
            MultivariateNormalTransition, params, key, n, dim=dim,
            n_bootstrap=n_bootstrap, scaling=scaling,
            bandwidth_selector=bandwidth_selector,
        )

    @staticmethod
    def device_required_nr(params, key, *, target_cv: float, min_n: int,
                           max_n: int, dim: int, scaling: float,
                           bandwidth_selector: Callable, n_bootstrap: int):
        """Traceable twin of ``AdaptivePopulationSize.update``'s bisection
        (reference ``pyabc/populationstrategy.py``). One key for every
        probe — the same common-random-numbers discipline as the host's
        per-call ``default_rng(0)``, which keeps cv(n) monotone-ish in
        n. Generic machinery: ``transition.util.device_required_nr``."""
        from .util import device_required_nr as _generic_nr

        def cv_at(n):
            return MultivariateNormalTransition.device_mean_cv(
                params, key, n, dim=dim, scaling=scaling,
                bandwidth_selector=bandwidth_selector,
                n_bootstrap=n_bootstrap,
            )

        return _generic_nr(
            cv_at, target_cv=target_cv, min_n=min_n, max_n=max_n,
        )

    def __repr__(self):
        return (f"MultivariateNormalTransition(scaling={self.scaling}, "
                f"bandwidth_selector={self.bandwidth_selector.__name__})")
