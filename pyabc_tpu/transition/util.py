"""Transition utilities.

Reference parity: ``pyabc/transition/util.py::smart_cov`` plus the bandwidth
rules from ``pyabc/transition/multivariatenormal.py::{scott_rule_of_thumb,
silverman_rule_of_thumb}``.
"""
from __future__ import annotations

import numpy as np


def smart_cov(X: np.ndarray, w: np.ndarray) -> np.ndarray:
    """Weighted covariance robust to degenerate input (pyabc smart_cov).

    Zero-variance directions get a small positive diagonal so the Cholesky
    factorization (and hence sampling) never fails; a single particle yields
    a small isotropic covariance.
    """
    X = np.asarray(X, np.float64)
    w = np.asarray(w, np.float64)
    w = w / w.sum()
    mean = w @ X
    centered = X - mean
    cov = (centered * w[:, None]).T @ centered
    # degenerate fixes
    d = X.shape[1]
    if len(X) == 1 or not np.all(np.isfinite(cov)):
        cov = np.eye(d) * 1e-4
    diag = np.diag(cov).copy()
    bad = diag <= 0
    if bad.any():
        fill = np.abs(mean) * 1e-4 + 1e-8
        cov[np.diag_indices(d)] = np.where(bad, fill, diag)
    return cov


def scott_rule_of_thumb(n_samples: float, dimension: int) -> float:
    """Scott bandwidth factor: n^(-1/(d+4)) (pyabc scott_rule_of_thumb)."""
    return n_samples ** (-1.0 / (dimension + 4))


def silverman_rule_of_thumb(n_samples: float, dimension: int) -> float:
    """Silverman factor: (4/(d+2))^(1/(d+4)) n^(-1/(d+4))
    (pyabc silverman_rule_of_thumb)."""
    return (4 / (dimension + 2)) ** (1 / (dimension + 4)) * n_samples ** (
        -1 / (dimension + 4)
    )
