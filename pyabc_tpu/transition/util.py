"""Transition utilities.

Reference parity: ``pyabc/transition/util.py::smart_cov`` plus the bandwidth
rules from ``pyabc/transition/multivariatenormal.py::{scott_rule_of_thumb,
silverman_rule_of_thumb}``.
"""
from __future__ import annotations

import numpy as np


def smart_cov(X: np.ndarray, w: np.ndarray) -> np.ndarray:
    """Weighted covariance robust to degenerate input (pyabc smart_cov).

    Zero-variance directions get a small positive diagonal so the Cholesky
    factorization (and hence sampling) never fails; a single particle yields
    a small isotropic covariance.
    """
    X = np.asarray(X, np.float64)
    w = np.asarray(w, np.float64)
    w = w / w.sum()
    mean = w @ X
    centered = X - mean
    cov = (centered * w[:, None]).T @ centered
    # degenerate fixes
    d = X.shape[1]
    if len(X) == 1 or not np.all(np.isfinite(cov)):
        cov = np.eye(d) * 1e-4
    diag = np.diag(cov).copy()
    bad = diag <= 0
    if bad.any():
        fill = np.abs(mean) * 1e-4 + 1e-8
        cov[np.diag_indices(d)] = np.where(bad, fill, diag)
    return cov


def scott_rule_of_thumb(n_samples: float, dimension: int) -> float:
    """Scott bandwidth factor: n^(-1/(d+4)) (pyabc scott_rule_of_thumb)."""
    return n_samples ** (-1.0 / (dimension + 4))


def silverman_rule_of_thumb(n_samples: float, dimension: int) -> float:
    """Silverman factor: (4/(d+2))^(1/(d+4)) n^(-1/(d+4))
    (pyabc silverman_rule_of_thumb)."""
    return (4 / (dimension + 2)) ** (1 / (dimension + 4)) * n_samples ** (
        -1 / (dimension + 4)
    )


#: escalating relative diagonal-jitter ladder for in-kernel Cholesky
#: retries (round 10 health guards): the host path's single 1e-10 retry
#: was matched in-kernel by ONE jittered re-factorization, which still
#: yields silent NaN factors for a covariance that needs more
#: regularization — and a NaN Cholesky poisons every proposal draw of
#: the rest of the chunk. Three rungs span numerically-marginal to
#: badly-conditioned; a matrix that stays non-finite past the ladder is
#: genuinely corrupt (NaN input) and is SURFACED through the health
#: word's psd_fail bit instead of swallowed.
CHOL_JITTER_LADDER = (1e-10, 1e-7, 1e-4)


def device_chol_guarded(cov):
    """Traceable Cholesky with jitter-escalation retry (single matrix).

    Returns ``(chol, cov_used, psd_failed)``: the factor from the first
    rung of ``CHOL_JITTER_LADDER`` (scaled by the mean diagonal) that
    came back finite, the (possibly jittered) covariance it factorizes —
    so the caller's precision/logdet stay consistent with the factor —
    and whether even the last rung failed: the caller feeds that flag to
    the health word rather than propagating NaN factors silently. All
    rungs are computed unconditionally (lax ``where`` semantics); the
    matrices are (d, d) with d small, so the retries are noise next to
    the surrounding refit."""
    import jax.numpy as jnp

    d = cov.shape[-1]
    chol = jnp.linalg.cholesky(cov)
    cov_used = cov
    tr = jnp.maximum(jnp.trace(cov) / d, 1e-30)
    for jit in CHOL_JITTER_LADDER:
        bad = ~jnp.all(jnp.isfinite(chol))
        cov_j = cov + jnp.eye(d, dtype=cov.dtype) * (jit * tr)
        chol = jnp.where(bad, jnp.linalg.cholesky(cov_j), chol)
        cov_used = jnp.where(bad, cov_j, cov_used)
    return chol, cov_used, ~jnp.all(jnp.isfinite(chol))


def device_chol_guarded_batched(covs):
    """Batched :func:`device_chol_guarded` for an (n, d, d) covariance
    field (LocalTransition's per-row factors). Each row escalates
    independently; returns ``(chols, psd_failed_any)``."""
    import jax.numpy as jnp

    d = covs.shape[-1]
    chols = jnp.linalg.cholesky(covs)
    covs_used = covs
    tr = jnp.maximum(
        jnp.trace(covs, axis1=-2, axis2=-1) / d, 1e-30
    )[..., None, None]
    for jit in CHOL_JITTER_LADDER:
        bad = ~jnp.all(jnp.isfinite(chols), axis=(-2, -1),
                       keepdims=True)
        covs_j = covs + jnp.eye(d, dtype=covs.dtype)[None] * (jit * tr)
        chols = jnp.where(bad, jnp.linalg.cholesky(covs_j), chols)
        covs_used = jnp.where(bad, covs_j, covs_used)
    return chols, covs_used, ~jnp.all(jnp.isfinite(chols))


def device_proposal_drift(fit_thetas, fit_w, new_thetas, new_w, vmask):
    """Traceable acceptance-weighted drift of a population vs the fitted
    proposal (the refit-cadence guard statistic, ISSUE 3 tentpole #1).

    Compares the weighted per-dimension mean and variance of the NEW
    accepted population against those of the population the carried
    proposal was FITTED on (both live inside the carried transition
    params as ``thetas`` / ``weights``):

    - mean shift, standardized by the fitted std;
    - relative variance change.

    Returns the max over valid dimensions of both terms — ``0`` means
    the accepted population still looks like the one the proposal was
    fitted on (sampling from the stale fit stays efficient); large
    values mean the population moved and the local covariances are
    stale. A zero-mass side (never-fitted placeholder, empty model)
    returns 0 — the kernel's forced-refit conditions own those cases.
    O(n * d): cheap enough to run EVERY generation so non-refit
    generations still measure how stale they are.
    """
    import jax.numpy as jnp

    sf = jnp.sum(fit_w)
    sn = jnp.sum(new_w)
    wf = fit_w / jnp.maximum(sf, 1e-38)
    wn = new_w / jnp.maximum(sn, 1e-38)
    mu_f = wf @ fit_thetas
    mu_n = wn @ new_thetas
    var_f = jnp.maximum(wf @ (fit_thetas**2) - mu_f**2, 0.0)
    var_n = jnp.maximum(wn @ (new_thetas**2) - mu_n**2, 0.0)
    # absolute floor + a relative one: a near-point-mass fitted dimension
    # must not turn a tiny absolute shift into an infinite drift
    denom = var_f + 1e-12 + 1e-8 * mu_f**2
    mean_shift = jnp.abs(mu_n - mu_f) / jnp.sqrt(denom)
    var_shift = jnp.abs(var_n - var_f) / denom
    per_dim = jnp.maximum(mean_shift, var_shift) * vmask
    drift = jnp.max(per_dim)
    return jnp.where((sf > 0) & (sn > 0), drift, 0.0)


def device_mean_cv(trans_cls, params, key, n, *, dim: int,
                   n_bootstrap: int, **fit_kwargs):
    """Traceable twin of :meth:`Transition.mean_cv` for ANY transition
    class with ``device_fit``/``device_logpdf`` twins (reference
    ``pyabc/transition/base.py::Transition.mean_cv`` /
    ``pyabc/cv/bootstrap.py``): bootstrap CV of the KDE density at
    resample size ``n`` (a traced int32), evaluated at the fitted
    particles and weighted by their weights. Padding lanes carry zero
    weight and contribute nothing on either side."""
    import jax
    import jax.numpy as jnp

    thetas, w = params["thetas"], params["weights"]
    n_cap = thetas.shape[0]
    logw = jnp.where(w > 0, jnp.log(jnp.maximum(w, 1e-38)), -jnp.inf)
    # bootstrap sample of size n inside static shapes: draw n_cap
    # ancestors, weight the first n uniformly, zero the rest
    boot_w = jnp.where(
        jnp.arange(n_cap) < n, 1.0 / jnp.maximum(n, 1), 0.0
    ).astype(thetas.dtype)

    def one_boot(k):
        idx = jax.random.categorical(k, logw, shape=(n_cap,))
        p = trans_cls.device_fit(
            thetas[idx], boot_w, dim=dim, **fit_kwargs,
        )
        return jax.vmap(lambda th: trans_cls.device_logpdf(th, p))(thetas)

    logdens = jax.vmap(one_boot)(jax.random.split(key, n_bootstrap))
    # CV is scale-invariant: shift by the per-point max log-density so
    # the f32 exp cannot overflow for concentrated late-generation KDEs
    # (an inf mean would NaN the CV and pin the bisection at max_n)
    dens = jnp.exp(logdens - logdens.max(axis=0, keepdims=True))
    mean = dens.mean(axis=0)
    std = dens.std(axis=0)
    cvs = jnp.where(mean > 0, std / mean, 0.0)
    return jnp.sum(w * cvs) / jnp.maximum(w.sum(), 1e-38)


def device_required_nr(cv_at, *, target_cv: float, min_n: int, max_n: int):
    """Traceable bisection twin of ``AdaptivePopulationSize.update``
    (reference ``pyabc/populationstrategy.py``) over an arbitrary
    ``cv_at(n)`` — e.g. the model-probability-weighted aggregate CV of
    K fitted transitions (reference ``calc_cv``). Smallest n in
    [min_n, max_n] whose CV is below ``target_cv``, or max_n when the
    target is unreachable."""
    import jax
    import jax.numpy as jnp

    cv_hi = cv_at(jnp.asarray(max_n, jnp.int32))

    def body(state):
        lo, hi = state
        mid = (lo + hi) // 2
        ok = cv_at(mid) <= target_cv
        return (jnp.where(ok, lo, mid + 1), jnp.where(ok, mid, hi))

    def bisect():
        _, hi = jax.lax.while_loop(
            lambda s: s[0] < s[1], body,
            (jnp.asarray(min_n, jnp.int32), jnp.asarray(max_n, jnp.int32)),
        )
        return hi

    # host short-circuit parity: an unreachable target returns max_n
    # without paying the ~log2(max_n) dead bisection probes
    return jax.lax.cond(
        cv_hi > target_cv, lambda: jnp.asarray(max_n, jnp.int32), bisect,
    )
