"""Discrete-parameter transitions.

Reference parity: ``pyabc/transition/randomwalk.py::DiscreteRandomWalkTransition``
and ``pyabc/transition/jump.py::DiscreteJumpTransition`` (names/locations vary
slightly across versions; semantics preserved).
"""
from __future__ import annotations

import numpy as np
import pandas as pd

from .base import DiscreteTransition
from .exceptions import NotEnoughParticles


class DiscreteRandomWalkTransition(DiscreteTransition):
    """Integer random walk: resample an ancestor, add a +-1/0 step per
    dimension (pyabc DiscreteRandomWalkTransition)."""

    def __init__(self, n_steps: int = 1,
                 p_l: float = 1.0 / 3, p_r: float = 1.0 / 3,
                 p_c: float = 1.0 / 3):
        self.n_steps = int(n_steps)
        total = p_l + p_r + p_c
        self.p_l, self.p_r, self.p_c = p_l / total, p_r / total, p_c / total

    def fit(self, X: pd.DataFrame, w: np.ndarray) -> None:
        self.store_fit_params(X, w)

    def rvs_single(self) -> pd.Series:
        idx = np.random.choice(len(self.X), p=self.w)
        theta = np.asarray(self.X.iloc[idx], np.float64).copy()
        for _ in range(self.n_steps):
            steps = np.random.choice(
                [-1, 0, 1], size=theta.shape, p=[self.p_l, self.p_c, self.p_r]
            )
            theta = theta + steps
        return pd.Series(theta, index=self.X.columns)

    def pdf(self, x: pd.Series | pd.DataFrame):
        """Probability of reaching x from the weighted ancestors by the walk."""
        arr = np.atleast_2d(np.asarray(x, np.float64))
        thetas = np.asarray(self.X, np.float64)
        # n_steps-fold convolution of the single-step pmf per dimension
        step_vals, step_probs = self._step_distribution()
        out = np.zeros(arr.shape[0])
        for q in range(arr.shape[0]):
            diff = arr[q][None, :] - thetas  # (n, d)
            p_dim = np.zeros_like(diff)
            for v, p in zip(step_vals, step_probs):
                p_dim += np.where(diff == v, p, 0.0)
            out[q] = float(np.sum(self.w * np.prod(p_dim, axis=1)))
        single = np.asarray(x).ndim == 1
        return float(out[0]) if single else out

    def _step_distribution(self):
        """Exact pmf of the sum of n_steps iid {-1,0,1} steps."""
        vals = {0: 1.0}
        for _ in range(self.n_steps):
            new: dict[int, float] = {}
            for v, p in vals.items():
                for s, ps in ((-1, self.p_l), (0, self.p_c), (1, self.p_r)):
                    new[v + s] = new.get(v + s, 0.0) + p * ps
            vals = new
        items = sorted(vals.items())
        return [v for v, _ in items], [p for _, p in items]


class DiscreteJumpTransition(DiscreteTransition):
    """Stay with probability p_stay, else jump uniformly over the domain
    (pyabc DiscreteJumpTransition). For a single discrete parameter column."""

    def __init__(self, domain, p_stay: float = 0.7):
        self.domain = np.asarray(domain)
        if len(self.domain) < 2:
            raise ValueError("domain must have at least 2 values")
        self.p_stay = float(p_stay)
        self.p_move = (1.0 - p_stay) / (len(self.domain) - 1)

    def fit(self, X: pd.DataFrame, w: np.ndarray) -> None:
        if X.shape[1] != 1:
            raise ValueError("DiscreteJumpTransition handles one parameter")
        self.store_fit_params(X, w)

    def rvs_single(self) -> pd.Series:
        idx = np.random.choice(len(self.X), p=self.w)
        val = float(np.asarray(self.X.iloc[idx])[0])
        if np.random.uniform() >= self.p_stay:
            others = self.domain[self.domain != val]
            val = float(np.random.choice(others))
        return pd.Series([val], index=self.X.columns)

    def pdf(self, x: pd.Series | pd.DataFrame):
        arr = np.atleast_1d(np.asarray(x, np.float64)).reshape(-1)
        anc = np.asarray(self.X, np.float64).reshape(-1)
        out = np.empty(arr.shape[0])
        for q, v in enumerate(arr):
            stay_mass = float(np.sum(self.w[anc == v]))
            move_mass = float(np.sum(self.w[anc != v]))
            out[q] = stay_mass * self.p_stay + move_mass * self.p_move
        single = np.asarray(x).ndim <= 1 and out.shape[0] == 1
        return float(out[0]) if single else out


class PerturbationKernel(DiscreteJumpTransition):
    """Alias kept for reference-name familiarity."""
