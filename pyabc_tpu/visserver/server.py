"""Dashboard web server for browsing a History database.

Reference parity: ``pyabc/visserver/server.py`` (Flask + bokeh dashboard:
runs overview, model probabilities, per-generation KDEs) and the
``abc-server <db>`` CLI entry point. The reference leans on Flask; this
environment has no Flask, so the dashboard is built on the stdlib
``http.server`` (ThreadingHTTPServer) with matplotlib-Agg-rendered PNGs —
zero extra dependencies, same browsing surface:

- ``/``                         runs in the db
- ``/abc/<id>``                 run detail: config, populations, plots
- ``/abc/<id>/plot/<name>.png`` diagnostic plots (epsilons, sample numbers,
                                acceptance rates, ESS, walltime,
                                model probabilities)
- ``/abc/<id>/kde/<m>/<param>.png?t=<t>``   1-d posterior KDE
- ``/abc/<id>/kde_matrix/<m>.png?t=<t>``    KDE matrix
- ``/api/<id>/populations``     JSON of the populations table
"""
from __future__ import annotations

import html
import io
import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import numpy as np

from ..storage.history import History


def _render_png(plot_fn) -> bytes:
    """Run a plot function (returning an Axes or Figure) to PNG bytes."""
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    out = plot_fn()
    if isinstance(out, np.ndarray):  # axes grid (plot_kde_matrix)
        out = out.flat[0]
    fig = out.get_figure() if hasattr(out, "get_figure") else out
    if fig is None:  # pragma: no cover - axes always carry a figure
        fig = plt.gcf()
    buf = io.BytesIO()
    fig.savefig(buf, format="png", dpi=96, bbox_inches="tight")
    plt.close(fig)
    return buf.getvalue()


_PAGE = """<!doctype html><html><head><title>{title}</title>
<style>
 body {{ font-family: sans-serif; margin: 2em; color: #222; }}
 table {{ border-collapse: collapse; }}
 td, th {{ border: 1px solid #ccc; padding: 4px 10px; text-align: right; }}
 th {{ background: #f0f0f0; }}
 img {{ max-width: 46%; margin: 6px; border: 1px solid #eee; }}
 a {{ color: #06c; }}
 code {{ background: #f6f6f6; padding: 1px 4px; }}
</style></head><body>{body}</body></html>"""


class AbcDashboard:
    """Routes + rendering over one History db (no server state)."""

    def __init__(self, db_url: str):
        self.db_url = db_url

    # -------------------------------------------------------------- helpers
    def _history(self, run_id: int | None = None) -> History:
        # one History per request: sqlite connections are thread-bound and
        # the ThreadingHTTPServer serves each request on its own thread
        return History(self.db_url, _id=run_id)

    def _param_names(self, h: History, m: int) -> list[str]:
        return h.get_parameter_names(m=m)

    # --------------------------------------------------------------- routes
    def index(self) -> str:
        h = self._history()
        runs = h.all_runs()
        rows = "".join(
            f"<tr><td><a href='/abc/{int(r.id)}'>{int(r.id)}</a></td>"
            f"<td>{html.escape(str(r.start_time))}</td>"
            f"<td>{html.escape(str(r.distance_function))}</td>"
            f"<td>{html.escape(str(r.epsilon_function))}</td></tr>"
            for r in runs.itertuples()
        )
        body = (f"<h1>ABC runs in <code>{html.escape(self.db_url)}</code>"
                f"</h1><table><tr><th>id</th><th>started</th>"
                f"<th>distance</th><th>epsilon</th></tr>{rows}</table>")
        return _PAGE.format(title="pyabc_tpu dashboard", body=body)

    def run_page(self, run_id: int) -> str:
        h = self._history(run_id)
        pops = h.get_all_populations()
        pops = pops[pops.t >= 0]
        rows = "".join(
            "<tr>" + "".join(
                f"<td>{html.escape(str(v))}</td>"
                for v in (int(r.t), f"{r.epsilon:.6g}", int(r.samples),
                          str(r.population_end_time))
            ) + "</tr>"
            for r in pops.itertuples()
        )
        plots = "".join(
            f"<img src='/abc/{run_id}/plot/{p}.png' alt='{p}'>"
            for p in ("epsilons", "eps_walltime", "sample_numbers",
                      "acceptance_rates", "effective_sample_sizes",
                      "walltime", "model_probabilities")
        )
        probs = h.get_model_probabilities(h.max_t)
        alive = [int(m) for m, p in probs["p"].items() if p > 0]
        kde_links = []
        for m in alive:
            names = self._param_names(h, m)
            kde_links.append(
                f"<li>model {m}: "
                + " ".join(
                    f"<a href='/abc/{run_id}/kde/{m}/{html.escape(nm)}.png'>"
                    f"{html.escape(nm)}</a>" for nm in names
                )
                + f" | <a href='/abc/{run_id}/kde_matrix/{m}.png'>matrix</a>"
                "</li>"
            )
        body = (
            f"<h1>ABC run {run_id}</h1>"
            f"<p><a href='/'>&larr; all runs</a> | "
            f"<a href='/api/{run_id}/populations'>populations JSON</a></p>"
            f"<h2>Populations</h2><table><tr><th>t</th><th>epsilon</th>"
            f"<th>samples</th><th>end time</th></tr>{rows}</table>"
            f"<h2>Posterior KDEs (final generation)</h2>"
            f"<ul>{''.join(kde_links)}</ul>"
            f"<h2>Diagnostics</h2>{plots}"
        )
        return _PAGE.format(title=f"ABC run {run_id}", body=body)

    def diagnostic_png(self, run_id: int, name: str) -> bytes:
        from ..visualization import diagnostics as d

        h = self._history(run_id)
        fns = {
            "epsilons": d.plot_epsilons,
            "eps_walltime": d.plot_eps_walltime,
            "sample_numbers": d.plot_sample_numbers,
            "acceptance_rates": d.plot_acceptance_rates_trajectory,
            "effective_sample_sizes": d.plot_effective_sample_sizes,
            "walltime": d.plot_total_walltime,
            "model_probabilities": d.plot_model_probabilities,
        }
        if name not in fns:
            raise KeyError(name)
        return _render_png(lambda: fns[name](h))

    def kde_png(self, run_id: int, m: int, param: str,
                t: int | None) -> bytes:
        from ..visualization.kde import plot_kde_1d_highlevel

        h = self._history(run_id)
        return _render_png(
            lambda: plot_kde_1d_highlevel(h, param, m=m, t=t)
        )

    def kde_matrix_png(self, run_id: int, m: int, t: int | None) -> bytes:
        from ..visualization.kde import plot_kde_matrix_highlevel

        h = self._history(run_id)
        return _render_png(lambda: plot_kde_matrix_highlevel(h, m=m, t=t))

    def observability_json(self) -> str:
        """This PROCESS's tracer/metrics snapshot (span counts + totals
        per name, instrument values) — live when the dashboard is
        embedded next to a running inference (``serve(block=False)``);
        an out-of-process dashboard reports its own (empty) state.

        Round 8: the snapshot's ``workers`` section surfaces the elastic
        pool when a broker is live in-process — per-worker liveness
        (idle age, presumed_dead), clock offset + RTT uncertainty,
        throughput counters, last error and departure tombstones — so a
        stalled ``broker.wait()`` diagnoses from the dashboard instead
        of a dark poll loop.

        Round 14: the ``tenants`` section aggregates each live
        serving-layer tenant's PRIVATE tracer/metrics namespace —
        embedded next to an ``abc-serve`` scheduler, concurrent runs
        show up side by side instead of interleaved through the
        process globals."""
        from ..observability import observability_snapshot

        return json.dumps(observability_snapshot(), default=str)

    def populations_json(self, run_id: int) -> str:
        h = self._history(run_id)
        pops = h.get_all_populations()
        out = []
        for r in pops.itertuples():
            out.append({
                "t": int(r.t), "epsilon": float(r.epsilon),
                "samples": int(r.samples),
                "population_end_time": str(r.population_end_time),
                "telemetry": h.get_telemetry(int(r.t)) if r.t >= 0 else {},
            })
        return json.dumps(out)


_ROUTES = [
    (re.compile(r"^/$"), "index"),
    (re.compile(r"^/abc/(\d+)$"), "run"),
    (re.compile(r"^/abc/(\d+)/plot/([a-z_]+)\.png$"), "plot"),
    (re.compile(r"^/abc/(\d+)/kde/(\d+)/([^/]+)\.png$"), "kde"),
    (re.compile(r"^/abc/(\d+)/kde_matrix/(\d+)\.png$"), "kde_matrix"),
    (re.compile(r"^/api/(\d+)/populations$"), "api_populations"),
    (re.compile(r"^/api/observability$"), "api_observability"),
]


def _make_handler(dash: AbcDashboard):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # quiet by default
            pass

        def _send(self, code: int, ctype: str, payload: bytes) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def do_GET(self):  # noqa: N802 - stdlib API
            url = urlparse(self.path)
            try:
                q = parse_qs(url.query)
                t = int(q["t"][0]) if "t" in q else None
                for pat, kind in _ROUTES:
                    mobj = pat.match(url.path)
                    if not mobj:
                        continue
                    g = mobj.groups()
                    if kind == "index":
                        return self._send(
                            200, "text/html",
                            dash.index().encode())
                    if kind == "run":
                        return self._send(
                            200, "text/html",
                            dash.run_page(int(g[0])).encode())
                    if kind == "plot":
                        return self._send(
                            200, "image/png",
                            dash.diagnostic_png(int(g[0]), g[1]))
                    if kind == "kde":
                        return self._send(
                            200, "image/png",
                            dash.kde_png(int(g[0]), int(g[1]), g[2], t))
                    if kind == "kde_matrix":
                        return self._send(
                            200, "image/png",
                            dash.kde_matrix_png(int(g[0]), int(g[1]), t))
                    if kind == "api_populations":
                        return self._send(
                            200, "application/json",
                            dash.populations_json(int(g[0])).encode())
                    if kind == "api_observability":
                        return self._send(
                            200, "application/json",
                            dash.observability_json().encode())
                self._send(404, "text/plain", b"not found")
            except Exception as exc:  # surface errors as 500s, keep serving
                self._send(500, "text/plain",
                           f"error: {exc!r}".encode())

    return Handler


def serve(db_url: str, host: str = "127.0.0.1", port: int = 8765,
          block: bool = True) -> ThreadingHTTPServer:
    """Serve the dashboard; ``block=False`` runs it on a daemon thread and
    returns the server (tests / embedding)."""
    dash = AbcDashboard(db_url)
    httpd = ThreadingHTTPServer((host, port), _make_handler(dash))
    if block:  # pragma: no cover - manual invocation
        print(f"pyabc_tpu dashboard on http://{host}:{httpd.server_port}")
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            httpd.server_close()
        return httpd
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    return httpd
