"""Web dashboard over a History database (reference parity:
``pyabc/visserver/server.py`` + the ``abc-server`` CLI)."""
from .server import AbcDashboard, serve

__all__ = ["AbcDashboard", "serve"]
