"""Execution contexts wrapping each SGE job's function call (reference
parity: ``pyabc/sge/execution_contexts.py::{DefaultContext,
ProfilingContext, NamedPrinter}``) — context managers entered around the
user function inside the worker job."""
from __future__ import annotations

import cProfile
import os
import sys


class DefaultContext:
    """No-op context (reference DefaultContext)."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class ProfilingContext:
    """cProfile the job; dump stats next to the job files (reference
    ProfilingContext)."""

    def __init__(self, directory: str | None = None):
        self.directory = directory
        self._prof = None

    def __enter__(self):
        self._prof = cProfile.Profile()
        self._prof.enable()
        return self

    def __exit__(self, *exc):
        self._prof.disable()
        directory = self.directory or os.getcwd()
        self._prof.dump_stats(
            os.path.join(directory, f"profile_{os.getpid()}.pstats")
        )
        return False


class NamedPrinter:
    """Prefix the job's stdout lines with its name (reference NamedPrinter:
    makes interleaved array-job logs attributable)."""

    def __init__(self, name: str):
        self.name = name
        self._orig = None

    def __enter__(self):
        self._orig = sys.stdout
        printer = self

        class _Prefixed:
            def write(self, text):
                if text.strip():
                    printer._orig.write(f"[{printer.name}] {text}")
                else:
                    printer._orig.write(text)

            def flush(self):
                printer._orig.flush()

        sys.stdout = _Prefixed()
        return self

    def __exit__(self, *exc):
        sys.stdout = self._orig
        return False
