"""SGE utilities (reference parity: ``pyabc/sge/util.py::sge_available``)."""
from __future__ import annotations

import shutil

from ..sampler.multicore import nr_cores_available  # single source of truth

__all__ = ["sge_available", "nr_cores_available"]


def sge_available() -> bool:
    """True when an SGE submission host is usable (qsub + qstat on PATH)."""
    return shutil.which("qsub") is not None and shutil.which("qstat") is not None
