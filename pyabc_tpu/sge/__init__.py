"""SGE batch-queue execution (reference parity: ``pyabc/sge/``)."""
from .sge import SGE, sge_available
from .execution_contexts import (
    DefaultContext,
    NamedPrinter,
    ProfilingContext,
)
from .util import nr_cores_available

__all__ = [
    "SGE",
    "sge_available",
    "DefaultContext",
    "ProfilingContext",
    "NamedPrinter",
    "nr_cores_available",
]
