"""SGE array-job map (reference parity: ``pyabc/sge/sge.py::SGE``).

``SGE().map(fn, args)`` pickles (fn, arg) pairs into a temp directory,
submits one SGE array job whose tasks each unpickle and evaluate one entry
(via ``python -m pyabc_tpu.sge.job``), polls ``qstat`` until the job
leaves the queue, and collects the pickled results in order.

Gated on ``qsub``/``qstat`` (``sge_available``). The job-side contract is
plain files, so tests can stand in a stub qsub that runs tasks locally —
the reference's multi-node-as-local-process testing pattern (SURVEY.md §4).
"""
from __future__ import annotations

import os
import pickle
import subprocess
import sys
import tempfile
import time

from .execution_contexts import DefaultContext
from .util import sge_available

_JOB_TEMPLATE = """#!/bin/bash
#$ -N {name}
#$ -t 1-{n_tasks}
#$ -o {tmp_dir}/logs
#$ -e {tmp_dir}/logs
#$ -cwd
{python} -m pyabc_tpu.sge.job {tmp_dir} $SGE_TASK_ID
"""


class SGE:
    """Map over an SGE cluster via array jobs (reference SGE).

    Parameters (reference names): ``name`` job name, ``memory`` / ``time_h``
    resource strings are accepted for parity but only embedded as comments
    (site-specific resource syntax varies), ``priority``, ``num_threads``,
    ``execution_context`` entered around each task, ``chunk_size`` args per
    task.
    """

    def __init__(self, name: str = "abc", memory: str = "3G",
                 time_h: int = 100, priority: int = 0, num_threads: int = 1,
                 execution_context=DefaultContext, chunk_size: int = 1,
                 poll_interval_s: float = 2.0):
        if not sge_available():
            raise RuntimeError(
                "SGE().map needs qsub/qstat on PATH; for single-host "
                "parallelism use MulticoreEvalParallelSampler, for TPU "
                "scale-out the default BatchedSampler with mesh=."
            )
        self.name = name
        self.memory = memory
        self.time_h = int(time_h)
        self.priority = int(priority)
        self.num_threads = int(num_threads)
        self.execution_context = execution_context
        self.chunk_size = int(chunk_size)
        self.poll_interval_s = float(poll_interval_s)

    def map(self, fn, args: list):
        tmp_dir = tempfile.mkdtemp(prefix="pyabc_tpu_sge_")
        os.makedirs(os.path.join(tmp_dir, "logs"), exist_ok=True)
        chunks = [
            args[i:i + self.chunk_size]
            for i in range(0, len(args), self.chunk_size)
        ]
        with open(os.path.join(tmp_dir, "function.pkl"), "wb") as fh:
            pickle.dump((fn, self.execution_context), fh)
        for i, chunk in enumerate(chunks, start=1):
            with open(os.path.join(tmp_dir, f"job_{i}.pkl"), "wb") as fh:
                pickle.dump(chunk, fh)
        script = _JOB_TEMPLATE.format(
            name=self.name, n_tasks=len(chunks), tmp_dir=tmp_dir,
            python=sys.executable,
        )
        script_path = os.path.join(tmp_dir, "submit.sh")
        with open(script_path, "w") as fh:
            fh.write(script)
        out = subprocess.run(
            ["qsub", "-terse", script_path], capture_output=True, text=True,
            check=True,
        )
        job_id = out.stdout.strip().split(".")[0]
        self._wait(job_id)
        results = []
        for i in range(1, len(chunks) + 1):
            res_path = os.path.join(tmp_dir, f"result_{i}.pkl")
            if not os.path.exists(res_path):
                raise RuntimeError(
                    f"SGE task {i} produced no result (logs in "
                    f"{tmp_dir}/logs)"
                )
            with open(res_path, "rb") as fh:
                results.extend(pickle.load(fh))
        return results

    def _wait(self, job_id: str) -> None:
        while True:
            out = subprocess.run(["qstat"], capture_output=True, text=True)
            if out.returncode != 0:
                # transient qstat failure: keep polling, never conclude
                # "job finished" from an error
                time.sleep(self.poll_interval_s)
                continue
            # whole-token match on the id column: job 123 must not match a
            # queued job 1234
            queued = {
                line.split()[0].split(".")[0]
                for line in out.stdout.splitlines()
                if line.strip() and line.split()[0][:1].isdigit()
            }
            if job_id not in queued:
                return
            time.sleep(self.poll_interval_s)
