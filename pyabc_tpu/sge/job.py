"""SGE task entry point: ``python -m pyabc_tpu.sge.job <tmp_dir> <task_id>``.

Unpickles (function, execution_context) + the task's argument chunk,
evaluates inside the context, writes ``result_<task_id>.pkl``.
(Reference parity: the job script body of ``pyabc/sge/sge.py``.)
"""
from __future__ import annotations

import os
import pickle
import sys


def main(tmp_dir: str, task_id: str) -> None:
    with open(os.path.join(tmp_dir, "function.pkl"), "rb") as fh:
        fn, context = pickle.load(fh)
    with open(os.path.join(tmp_dir, f"job_{task_id}.pkl"), "rb") as fh:
        chunk = pickle.load(fh)
    # context may be a class (no-arg construction) or a pre-configured
    # instance (NamedPrinter("w1"), ProfilingContext(directory=...))
    ctx = context() if isinstance(context, type) else context
    with ctx:
        results = [fn(arg) for arg in chunk]
    out = os.path.join(tmp_dir, f"result_{task_id}.pkl")
    with open(out + ".tmp", "wb") as fh:
        pickle.dump(results, fh)
    os.replace(out + ".tmp", out)  # atomic: pollers never see partials


if __name__ == "__main__":
    main(sys.argv[1], sys.argv[2])
