"""Summary-statistic transforms (reference ``pyabc/sumstat/``)."""
from .base import IdentitySumstat, PredictorSumstat, Sumstat

__all__ = ["Sumstat", "IdentitySumstat", "PredictorSumstat"]
