"""Summary-statistic transforms (reference ``pyabc/sumstat/``)."""
from .base import IdentitySumstat, PredictorSumstat, Sumstat
from .device import device_fit_plan, mirror_fitted_params, plan_cache_token

__all__ = [
    "Sumstat",
    "IdentitySumstat",
    "PredictorSumstat",
    "device_fit_plan",
    "mirror_fitted_params",
    "plan_cache_token",
]
