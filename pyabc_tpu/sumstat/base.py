"""Summary-statistic layer: transforms of raw simulator output.

Reference parity: ``pyabc/sumstat/base.py::{Sumstat, IdentitySumstat}`` and
``pyabc/sumstat/subset.py`` era trafos (SURVEY.md §2.2 last row). A Sumstat
maps the FLAT raw sum-stat vector (see ``SumStatSpec``) to the feature
vector the distance actually compares; ``PredictorSumstat`` learns that
mapping each generation (Fearnhead-Prangle 2012: s(x) = E[theta | x]
regression estimates are near-optimal summaries).

TPU-first contract mirroring Distance: ``device_params(t)`` is a pytree of
arrays swapped per generation (refits never recompile),
``device_fn(spec) -> fn(x_flat, params) -> s`` is traceable and runs inside
the generation kernel.
"""
from __future__ import annotations

from typing import Callable, Sequence

import jax.numpy as jnp
import numpy as np

from ..core.sumstat_spec import SumStatSpec
from ..predictor import Predictor


class Sumstat:
    """Identity base class (pyabc Sumstat/IdentitySumstat without trafos)."""

    def initialize(self, t: int, get_all_sum_stats: Callable | None = None,
                   x_0=None, spec: SumStatSpec | None = None) -> None:
        self.spec = spec

    def configure_sampler(self, sampler) -> None:
        pass

    def update(self, t: int, population=None,
               get_all_sum_stats: Callable | None = None) -> bool:
        """Refit on generation data; True if the transform changed."""
        return False

    def out_dim(self, in_dim: int) -> int:
        return in_dim

    def __call__(self, flat: np.ndarray) -> np.ndarray:
        """Host transform of a flat (S,) or (n, S) sum-stat array."""
        return np.asarray(flat, np.float64)

    # ---------------------------------------------------------------- device
    def is_device_compatible(self) -> bool:
        return True

    def device_params(self, t: int | None = None):
        return ()

    def device_fn(self, spec: SumStatSpec):
        def fn(x, params):
            return x

        return fn

    def requires_calibration(self) -> bool:
        return False

    def __repr__(self):
        return f"{type(self).__name__}()"


class IdentitySumstat(Sumstat):
    """Raw statistics, optionally expanded through elementwise trafos
    (pyabc IdentitySumstat(trafos=...)): e.g. ``[lambda x: x,
    lambda x: x**2]`` doubles the feature vector with squares."""

    def __init__(self, trafos: Sequence[Callable] | None = None):
        self.trafos = list(trafos) if trafos is not None else None

    def out_dim(self, in_dim: int) -> int:
        return in_dim * (len(self.trafos) if self.trafos else 1)

    def __call__(self, flat: np.ndarray) -> np.ndarray:
        flat = np.asarray(flat, np.float64)
        if not self.trafos:
            return flat
        return np.concatenate([np.asarray(tr(flat)) for tr in self.trafos],
                              axis=-1)

    def device_fn(self, spec: SumStatSpec):
        trafos = self.trafos

        def fn(x, params):
            if not trafos:
                return x
            return jnp.concatenate([jnp.asarray(tr(x)) for tr in trafos],
                                   axis=-1)

        return fn

    def __repr__(self):
        n = len(self.trafos) if self.trafos else 1
        return f"IdentitySumstat(trafos={n})"


class PredictorSumstat(Sumstat):
    """Learned statistics s(x) = predicted theta (pyabc PredictorSumstat;
    Fearnhead-Prangle).

    Each generation the predictor refits theta ~ x on the accepted
    population (plus, when available, recorded rejected simulations carry no
    thetas in the ring — the accepted set is the reference's default fit
    set too), and the fitted regression becomes the next generation's
    summary transform. Until first fit the transform is the identity.

    ``fit_every``: refit cadence; 1 = every generation.
    """

    def __init__(self, predictor: Predictor, normalize_labels: bool = True,
                 fit_every: int = 1, min_samples: int | None = None):
        self.predictor = predictor
        self.normalize_labels = normalize_labels
        self.fit_every = int(fit_every)
        self.min_samples = min_samples
        self._out_dim: int | None = None
        self._last_fit_t: int | None = None

    def out_dim(self, in_dim: int) -> int:
        return self._out_dim if self._out_dim is not None else in_dim

    def update(self, t: int, population=None,
               get_all_sum_stats: Callable | None = None) -> bool:
        if population is None:
            return False
        if (self._last_fit_t is not None
                and t - self._last_fit_t < self.fit_every):
            return False
        x = np.asarray(population.sumstats, np.float64)
        # fit targets: the free parameters of the dominant model's space
        # (multi-model: thetas are zero-padded to d_max; regression on the
        # padded matrix is well-defined — padded columns predict constants)
        y = np.asarray(population.thetas, np.float64)
        w = np.asarray(population.weights, np.float64)
        need = self.min_samples if self.min_samples is not None else (
            x.shape[1] + 2
        )
        if len(x) < need:
            return False
        self.predictor.fit(x, y, w)
        self._out_dim = y.shape[1]
        self._last_fit_t = t
        return True

    def __call__(self, flat: np.ndarray) -> np.ndarray:
        if not self.predictor.fitted:
            return np.asarray(flat, np.float64)
        return np.asarray(self.predictor.predict(flat), np.float64)

    def is_device_compatible(self) -> bool:
        return self.predictor.is_device_compatible()

    def device_params(self, t: int | None = None):
        if not self.predictor.fitted:
            return ()
        return self.predictor.device_params()

    def device_fn(self, spec: SumStatSpec):
        predictor = self.predictor

        def fn(x, params):
            # read .fitted at TRACE time (inside fn), not at closure build:
            # the first fit changes the dyn pytree structure, which triggers
            # a retrace, which re-evaluates this branch with fitted=True
            if not predictor.fitted:
                return x
            return predictor.device_predict(x, params)

        return fn

    def requires_calibration(self) -> bool:
        return False

    def __repr__(self):
        return f"PredictorSumstat({self.predictor!r})"
