"""Device-native learned summary statistics (ISSUE 20 tentpole).

``PredictorSumstat`` historically forced the fused loop into the
crippled ``sumstat_refit`` dispatch mode: depth-1 pipeline, float32
fetch, no speculation, no checkpoints, and a HOST-side predictor refit
at every chunk boundary — and it was the recurring refusal reason in
every capability gate (sharded kernel, segmented early reject,
in-kernel calibration, look-ahead).

This module decides when the fit itself can move INTO the kernel
(:mod:`pyabc_tpu.ops.fit`): the resolved *device-fit plan* is a small
static config the multigen kernel specializes on. Under a plan the
fitted parameters ride the chunk carry as constant device operands
(``dist_w["ss"]``), the kernel refits them at the boundary generation
from the accepted reservoir (riding the collectives the cadence refit
already pays — zero new host syncs), and the packed fetch ships
TRANSFORMED C'-dim rows instead of raw S-dim statistics.

What stays host-side keeps an actionable reason string
(:func:`device_fit_plan` returns ``(None, reason)``), surfaced through
the capability-fallback telemetry like every other gate in the repo.
"""
from __future__ import annotations

import numpy as np

from ..predictor.predictor import (
    GPPredictor,
    LassoPredictor,
    LinearPredictor,
    MLPPredictor,
    ModelSelectionPredictor,
)
from .base import PredictorSumstat


def device_fit_plan(distance, *, total_size: int, d_max: int,
                    sharded_n: int | None = None) -> tuple[dict | None,
                                                           str | None]:
    """Resolve the in-kernel fit plan for a learned-sumstat distance, or
    the actionable reason it stays on the host path.

    ``(plan, None)`` means the multigen kernel can own the boundary
    refit: ``plan`` is a static config (hashable via
    :func:`plan_cache_token`) naming the fit kind and its
    hyperparameters. ``(None, reason)`` means the legacy host-refit
    dispatch mode serves the config; the reason lands in the
    capability-fallback telemetry.

    The resolution is STATIC — predictor type and hyperparameters only.
    Whether the predictor is actually fitted (the generation-0 host fit
    seeds the carried parameters and fixes the C' feature dimension) is
    a runtime question the fused loop checks separately.
    """
    sumstat = getattr(distance, "sumstat", None)
    if sumstat is None:
        return None, "distance has no learned sumstat transform"
    if not isinstance(sumstat, PredictorSumstat):
        return None, (
            f"{type(sumstat).__name__} is a fixed transform, not a "
            f"fitted predictor — nothing to refit in-kernel"
        )
    if sumstat.fit_every != 1:
        return None, (
            f"fit_every={sumstat.fit_every} host cadence control: the "
            f"in-kernel fit runs at every chunk boundary; drop "
            f"fit_every (or set 1) for device-native fits"
        )
    pred = sumstat.predictor
    need = (int(sumstat.min_samples) if sumstat.min_samples is not None
            else total_size + 2)
    if isinstance(pred, ModelSelectionPredictor):
        return None, (
            "ModelSelectionPredictor's cross-validated winner selection "
            "is host control flow (per-candidate fits + a validation "
            "split); the host-refit path serves it — pick the winning "
            "predictor directly for device-native fits"
        )
    if isinstance(pred, GPPredictor):
        return None, (
            "GPPredictor subsamples training points with host RNG and "
            "solves a dense kernel system per fit; the host-refit path "
            "serves it — LinearPredictor/MLPPredictor fit on-device"
        )
    if isinstance(pred, LassoPredictor):
        return None, (
            "LassoPredictor's ISTA proximal loop fits host-side (L1 "
            "thresholding has no bounded-cost in-kernel form here); "
            "the host-refit path serves it — LinearPredictor fits "
            "on-device"
        )
    if isinstance(pred, MLPPredictor):
        if sharded_n:
            return None, (
                "MLPPredictor's warm-started Adam steps refit on the "
                "gathered reservoir; the sharded kernel serves LINEAR "
                "device fits only — drop sharding or switch to "
                "LinearPredictor"
            )
        return {
            "kind": "mlp",
            "out_dim": int(d_max),
            "need": need,
            "lr": float(pred.lr),
            "n_steps": min(int(pred.n_steps), 100),
        }, None
    if isinstance(pred, LinearPredictor):
        return {
            "kind": "linear",
            "out_dim": int(d_max),
            "need": need,
            "alpha": float(pred.alpha),
        }, None
    return None, (
        f"{type(pred).__name__} has no traceable in-kernel fit twin; "
        f"the host-refit path serves it"
    )


def plan_cache_token(plan: dict | None) -> tuple | None:
    """Hashable kernel-cache token of a device-fit plan (sorted items)."""
    if plan is None:
        return None
    return tuple(sorted(plan.items()))


def seed_params_ready(distance) -> bool:
    """True when the generation-0 host fit has seeded the predictor, so
    the carry's ``dist_w["ss"]`` pytree has its final (fitted)
    structure and the C' feature dimension is fixed."""
    sumstat = getattr(distance, "sumstat", None)
    return (isinstance(sumstat, PredictorSumstat)
            and sumstat.predictor.fitted)


def mirror_fitted_params(distance, ssp_host, t: int) -> None:
    """Write the kernel's boundary-fit parameters back into the HOST
    predictor, so host state (checkpoint carry rebuilds, repr-level
    diagnostics, a later host ``predict``) reflects the device fit.

    ``ssp_host`` is the fetched ``dist_w_next["ss"]`` pytree for the
    boundary generation (numpy, float32). The float32 values are stored
    as-is: ``device_params()`` casts to float32 on the way back, so a
    resume-rebuilt carry round-trips BIT-IDENTICAL to the carried
    device operands (the preempt-matrix contract).
    """
    sumstat = distance.sumstat
    pred = sumstat.predictor
    if isinstance(pred, MLPPredictor):
        pred._params = [
            {"w": np.asarray(layer["w"], np.float32),
             "b": np.asarray(layer["b"], np.float32)}
            for layer in ssp_host["layers"]
        ]
        pred._mu = np.asarray(ssp_host["mu"], np.float32)
        pred._sd = np.asarray(ssp_host["sd"], np.float32)
        pred._ymu = np.asarray(ssp_host["ymu"], np.float32)
        pred._ysd = np.asarray(ssp_host["ysd"], np.float32)
        sumstat._out_dim = int(np.asarray(ssp_host["ymu"]).shape[-1])
    else:
        pred._W = np.asarray(ssp_host["W"], np.float32)
        pred._b = np.asarray(ssp_host["b"], np.float32)
        pred._mu = np.asarray(ssp_host["mu"], np.float32)
        pred._sd = np.asarray(ssp_host["sd"], np.float32)
        sumstat._out_dim = int(np.asarray(ssp_host["b"]).shape[-1])
    sumstat._last_fit_t = int(t)
